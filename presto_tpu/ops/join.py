"""Join kernels: sorted-lookup equi-join.

The TPU-native replacement for Presto's hash join (reference
presto-main/.../operator/HashBuilderOperator.java:51, LookupJoinOperator.java,
PagesHash.java, JoinProbe.java): the build side is sorted by key on device
once; each probe row binary-searches it (``jnp.searchsorted``, O(log n)
vectorized across all probe lanes) and gathers the payload. Static shapes
throughout: the output has the probe's capacity, with the row mask narrowed
for misses (inner) or payload validity cleared (left outer).

``lookup_join`` assumes *unique build keys* — the PK-FK joins that dominate
TPC-H/TPC-DS; ``expand_join`` handles many-to-many with a static expansion
factor. Key tuples of any arity compare lexicographically (per-column i64 /
IEEE-total-order u64 operands + a vectorized composite binary search) — the
same generality as Presto's compiled channel-tuple comparators
(sql/gen/JoinCompiler.java).

SQL semantics: NULL keys never match (either side).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .prefix import prefix_sum
from .. import types as T
from ..batch import Batch, Column, Schema


def _key_sentinel(dtype):
    if dtype == jnp.uint64:
        return jnp.asarray(jnp.iinfo(jnp.uint64).max, dtype=jnp.uint64)
    return jnp.asarray(jnp.iinfo(jnp.int64).max, dtype=jnp.int64)


def _key_arrays(batch: Batch, key_cols: Sequence[int]
                ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Per-column comparable key operands + combined key validity.

    Integer-family columns (ints, dates, decimals, dictionary codes,
    booleans) become i64; floating columns map through the IEEE-754
    total-order bit trick to u64 (monotone, exact — no truncation). Any
    arity is supported; tuples compare lexicographically downstream
    (reference sql/gen/JoinCompiler.java hashes/compares arbitrary
    channel tuples)."""
    ops: List[jnp.ndarray] = []
    valid: Optional[jnp.ndarray] = None
    for i in key_cols:
        c = batch.columns[i]
        d = c.data
        if getattr(d, "ndim", 1) == 2:
            # long-decimal limb pairs: two lexicographic operands
            # (signed hi, unsigned-ordered lo) — downstream compares
            # key tuples generically, so arity just grows by one
            from .int128 import SIGN64
            ops.append(d[..., 0])
            ops.append(d[..., 1] ^ SIGN64)
            valid = c.validity if valid is None else valid & c.validity
            continue
        if jnp.issubdtype(d.dtype, jnp.floating):
            # +0.0 canonicalization (-0.0 + 0.0 == +0.0): SQL equality
            # joins the two zeros. NaN keys compare by bit pattern
            # (self-equal), i.e. grouping semantics.
            d = d.astype(jnp.float64) + 0.0
            bu = jax.lax.bitcast_convert_type(d, jnp.uint64)
            top = jnp.uint64(1) << jnp.uint64(63)
            d = jnp.where((bu >> jnp.uint64(63)) == 0, bu | top, ~bu)
        elif d.dtype == jnp.bool_:
            d = d.astype(jnp.int64)
        else:
            d = d.astype(jnp.int64)
        ops.append(d)
        valid = c.validity if valid is None else valid & c.validity
    return ops, valid


def build_sorted(build: Batch, key_cols: Sequence[int]):
    """Sort the build side lexicographically by the key tuple; dead and
    null-key rows to the end (their operands overwritten with per-dtype
    max sentinels, so the arrays stay fully sorted).

    Returns (sorted_key_ops, sorted_live, permutation) for probing; the
    permutation reorders build payload columns on demand.
    """
    ops, kvalid = _key_arrays(build, key_cols)
    live = build.row_mask & kvalid
    dead_rank = jnp.where(live, 0, 1).astype(jnp.int32)
    idx = jnp.arange(build.capacity, dtype=jnp.int32)
    out = jax.lax.sort([dead_rank] + ops + [idx], num_keys=1 + len(ops),
                       is_stable=True)
    perm = out[-1]
    slive = jnp.take(live, perm, axis=0)
    s_ops = [jnp.where(slive, op, _key_sentinel(op.dtype))
             for op in out[1:-1]]
    return s_ops, slive, perm


def prepare_build(build: Batch, key_cols: Sequence[int]):
    """One-time build-side preparation (sorted key operands + live mask +
    permutation) shared by every probe batch of a join — the role of the
    reference's LookupSource, built once by HashBuilderOperator and probed
    by many LookupJoinOperators. Pure arrays (a pytree), so it crosses
    jit boundaries and can be computed once per build under jit."""
    return build_sorted(build, key_cols)


def prepare_direct(build: Batch, key_cols: Sequence[int], lo0,
                   size: int):
    """Direct-address lookup table for a single integer key with a
    host-known bounded range — the BigintGroupByHash-style dense-int
    fast path applied to joins (reference BigintGroupByHash.java's array
    mode; PagesHash replaced by addressing).

    TPU rationale: random gathers run at ~55M/s on v5e, and the sorted
    path's binary search spends O(log n) gathers per probe row; a direct
    table answers [lo, hi) of a probe key's sorted match run in TWO
    gathers, independent of build size.

    Returns (lo0, lo_table, cnt_table, s_ops, slive, perm): tables are
    indexed by (key - lo0); empty slots hold (n, 0)."""
    s_ops, slive, perm = build_sorted(build, key_cols)
    n = s_ops[0].shape[0]
    off = jnp.clip(s_ops[0] - lo0, 0, size - 1).astype(jnp.int32)
    tgt = jnp.where(slive, off, size)       # dead rows -> overflow slot
    idx = jnp.arange(n, dtype=jnp.int32)
    lo_table = jnp.full(size + 1, n, dtype=jnp.int32) \
        .at[tgt].min(idx)[:size]
    cnt_table = jnp.zeros(size + 1, dtype=jnp.int32) \
        .at[tgt].add(jnp.int32(1))[:size]
    return (jnp.asarray(lo0, dtype=jnp.int64), lo_table, cnt_table,
            s_ops, slive, perm)


#: largest composite slot-table size a planner-keyed direct build may
#: allocate (slots x 2 x i32 = 512MB of HBM at the cap); the planner
#: gate (optimizer._attach_join_strategy) and the executor both respect
#: it, so key_bounds on a JoinNode always fit
DIRECT_KEYED_LIMIT = 1 << 26


def direct_keyed_plan(key_bounds, limit: int = DIRECT_KEYED_LIMIT):
    """Host-static (los, sizes, K) for a planner-bounded multi-key
    direct-address table, or None when it cannot engage: every key needs
    a hard [lo, hi] and the mixed-radix composite product must stay
    under ``limit`` — the join-side mirror of
    ``ops/aggregation.dense_group_plan``'s dispatch rule."""
    if not key_bounds or any(b is None for b in key_bounds):
        return None
    los: List[int] = []
    sizes: List[int] = []
    K = 1
    for lo, hi in key_bounds:
        if hi < lo:
            return None
        span = int(hi) - int(lo) + 1
        los.append(int(lo))
        sizes.append(span)
        K *= span
        if K > limit:
            return None
    return tuple(los), tuple(sizes), K


def _composite_code(ops: Sequence[jnp.ndarray], los, sizes):
    """(code, in_domain) of key-operand tuples against per-key
    [lo, lo+size) domains: code is the mixed-radix slot index — the same
    composite i32 code ``dense_group_plan`` builds for GROUP BY, minus
    the NULL component (null keys never match a join). ``los``/``sizes``
    index positionally (host tuples or traced i64 arrays both work)."""
    code = jnp.zeros(ops[0].shape, dtype=jnp.int64)
    ind = jnp.ones(ops[0].shape, dtype=bool)
    for i, op in enumerate(ops):
        lo = los[i]
        size = sizes[i]
        off = op.astype(jnp.int64) - lo
        ind = ind & (off >= 0) & (off < size)
        code = code * size + jnp.clip(off, 0, size - 1)
    return code, ind


def prepare_direct_keyed(build: Batch, key_cols: Sequence[int],
                         los: Sequence[int], sizes: Sequence[int],
                         size: int):
    """Multi-key direct-address table from PLANNER-PROMISED key bounds
    (``JoinNode.key_bounds``): composite mixed-radix slot per key tuple,
    answered in TWO gathers per probe lane regardless of arity or build
    size. Table capacity is host-known at PLAN time, so every batch of
    every query sharing the plan reuses one executable shape.

    Live build keys outside their promised bounds land in the overflow
    slot (they can never match) — the executor independently raises
    STATS_BOUND_VIOLATION for such rows through the row-error channel
    (the ``dense_group_plan`` contract), so an overclaiming connector
    fails the query instead of silently dropping matches.

    Returns (los, sizes, lo_table, cnt_table, s_ops, slive, perm)."""
    s_ops, slive, perm = build_sorted(build, key_cols)
    n = s_ops[0].shape[0]
    code, inr = _composite_code(s_ops, los, sizes)
    # lexicographic sort == composite-code sort inside the domain, so
    # equal-tuple runs are contiguous and [lo, lo+cnt) is exact
    tgt = jnp.where(slive & inr, code, size).astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    lo_table = jnp.full(size + 1, n, dtype=jnp.int32) \
        .at[tgt].min(idx)[:size]
    cnt_table = jnp.zeros(size + 1, dtype=jnp.int32) \
        .at[tgt].add(jnp.int32(1))[:size]
    return (jnp.asarray(los, dtype=jnp.int64),
            jnp.asarray(sizes, dtype=jnp.int64),
            lo_table, cnt_table, s_ops, slive, perm)


def _is_direct(prepared) -> bool:
    return prepared is not None and len(prepared) == 6


def _is_direct_keyed(prepared) -> bool:
    return prepared is not None and len(prepared) == 7


def is_direct_prepared(prepared) -> bool:
    """Either direct layout (single-key measured or multi-key planner
    bounds) — the dispatch the executors report as strategy=direct."""
    return _is_direct(prepared) or _is_direct_keyed(prepared)


def _split_prepared(prepared):
    if _is_direct(prepared):
        return prepared[3], prepared[4], prepared[5]
    if _is_direct_keyed(prepared):
        return prepared[4], prepared[5], prepared[6]
    return prepared


def direct_slot_codes(q_ops, prepared):
    """(slot, in_domain) probe-side addressing of a direct prepared —
    slot is a clipped i32 index into the lookup tables. Shared by the
    XLA probe path and the Pallas probe kernel so the two stay
    row-exact by construction."""
    if _is_direct(prepared):
        lo0, lo_table = prepared[0], prepared[1]
        size = lo_table.shape[0]
        off = q_ops[0] - lo0
        inr = (off >= 0) & (off < size)
        return jnp.clip(off, 0, size - 1).astype(jnp.int32), inr
    los, sizes, lo_table = prepared[0], prepared[1], prepared[2]
    size = lo_table.shape[0]
    code, inr = _composite_code(q_ops, los, sizes)
    return jnp.clip(code, 0, size - 1).astype(jnp.int32), inr


def _range_lookup(q_ops, prepared):
    """Per-probe-lane [lo, hi) over the SORTED build — via the direct
    table (2 gathers, single-key or composite) or composite binary
    search (2 log n gathers)."""
    if is_direct_prepared(prepared):
        s_ops = _split_prepared(prepared)[0]
        lo_table, cnt_table = ((prepared[1], prepared[2])
                               if _is_direct(prepared)
                               else (prepared[2], prepared[3]))
        n = s_ops[0].shape[0]
        idx, inr = direct_slot_codes(q_ops, prepared)
        lo = jnp.where(inr, jnp.take(lo_table, idx, axis=0), n)
        cnt = jnp.where(inr, jnp.take(cnt_table, idx, axis=0), 0)
        return lo.astype(jnp.int32), (lo + cnt).astype(jnp.int32)
    s_ops, slive, _ = prepared
    lo = _lex_searchsorted(s_ops, q_ops, side="left")
    hi = _lex_searchsorted(s_ops, q_ops, side="right")
    return lo, hi


def _point_lookup(q_ops, prepared):
    """(pos, hit) of each probe lane's first match in the sorted build."""
    if is_direct_prepared(prepared):
        lo, hi = _range_lookup(q_ops, prepared)
        n = _split_prepared(prepared)[0][0].shape[0]
        return jnp.clip(lo, 0, n - 1), hi > lo
    s_ops, slive, _ = prepared
    pos = _lex_searchsorted(s_ops, q_ops, side="left")
    pos = jnp.minimum(pos, s_ops[0].shape[0] - 1)
    hit = _tuple_eq(s_ops, q_ops, pos) & jnp.take(slive, pos, axis=0)
    return pos, hit


def _lex_searchsorted(s_ops: Sequence[jnp.ndarray],
                      q_ops: Sequence[jnp.ndarray],
                      side: str) -> jnp.ndarray:
    """Vectorized binary search of query tuples in lexicographically
    sorted operand arrays — searchsorted generalized to composite keys.
    O(log n) gathers per key column."""
    n = s_ops[0].shape[0]
    lo = jnp.zeros(q_ops[0].shape, dtype=jnp.int32)
    hi = jnp.full_like(lo, n)

    def go_right(mid):
        # side=left:  s[mid] <  q   |   side=right:  s[mid] <= q
        less = jnp.zeros(mid.shape, dtype=bool)
        eq = jnp.ones(mid.shape, dtype=bool)
        for s, q in zip(s_ops, q_ops):
            sv = jnp.take(s, mid, axis=0)
            less = less | (eq & (sv < q))
            eq = eq & (sv == q)
        return (less | eq) if side == "right" else less

    def body(_, lh):
        lo, hi = lh
        mid = (lo + hi) >> 1
        r = go_right(mid)
        return (jnp.where(r, mid + 1, lo), jnp.where(r, hi, mid))

    lo, hi = jax.lax.fori_loop(0, max(n.bit_length(), 1), body, (lo, hi))
    return lo


def _tuple_eq(s_ops, q_ops, pos) -> jnp.ndarray:
    eq = jnp.ones(pos.shape, dtype=bool)
    for s, q in zip(s_ops, q_ops):
        eq = eq & (jnp.take(s, pos, axis=0) == q)
    return eq


def lookup_join(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str = "inner",
    prepared=None,
) -> Batch:
    """Join probe against unique-key build side.

    join_type: 'inner' | 'left' (probe-preserving).
    Output schema = probe columns + named build payload columns.
    ``prepared`` (from prepare_build) skips re-sorting the build side.
    """
    assert join_type in ("inner", "left")
    prepared = prepared or build_sorted(build, build_keys)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    pos, hit = _point_lookup(q_ops, prepared)
    match = probe.row_mask & pvalid & hit

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = list(probe.columns)
    for ci, name in zip(payload, payload_names):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        out_fields.append((name, c.type))
        out_cols.append(Column(
            c.type,
            jnp.take(sdata, pos, axis=0),
            jnp.take(svalid, pos, axis=0) & match,
            c.dictionary,
        ))
    if join_type == "inner":
        mask = match
    else:
        mask = probe.row_mask
    return Batch(Schema(out_fields), out_cols, mask)


def match_count_max(
    probe: Batch, build: Batch,
    probe_keys: Sequence[int], build_keys: Sequence[int],
    prepared=None,
) -> jnp.ndarray:
    """Max build matches for any live probe key (device scalar).

    The skew fallback: for non-skewed builds the executor sizes
    ``expand_join`` from the probe-independent ``max_multiplicity`` bound
    (one readback per build); when that bound exceeds SKEW_MATCH_LIMIT it
    syncs this per (probe, build) pair instead, so only probe batches
    that actually hit the hot key pay the chunked skew loop — the
    capacity analogue of Presto's PositionLinks chain length (reference
    operator/ArrayPositionLinks.java).
    """
    prepared = prepared or build_sorted(build, build_keys)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    live = probe.row_mask & pvalid
    # live build rows sort before the dead-sentinel tail, so [lo, hi)
    # spans only live matches
    lo, hi = _range_lookup(q_ops, prepared)
    cnt = jnp.where(live, hi - lo, 0)
    return jnp.max(cnt) if cnt.shape[0] else jnp.asarray(0)


def max_multiplicity(prepared) -> jnp.ndarray:
    """Max live-key multiplicity of a PREPARED build side (device scalar).

    A probe-independent upper bound on ``match_count_max`` for EVERY probe
    batch: no probe key can match more build rows than the most frequent
    build key has. The executor reads this back ONCE per build and reuses
    it as the static expansion factor for all probe batches — replacing a
    per-probe-batch ``match_count_max`` sync (each a full tunnel RTT).
    Mirrors the reference's build-side PositionLinks, whose chain lengths
    are likewise a property of the build alone (reference
    operator/ArrayPositionLinks.java).
    """
    if is_direct_prepared(prepared):
        cnt_table = prepared[2] if _is_direct(prepared) else prepared[3]
        if cnt_table.shape[0] == 0:
            return jnp.asarray(0, dtype=jnp.int64)
        # keyed tables route bound-violating build rows to the overflow
        # slot, so the table max alone would undercount a (failing)
        # query's multiplicity — but such queries die on the error
        # channel before any expansion sizing matters
        return jnp.max(cnt_table).astype(jnp.int64)
    s_ops, slive, _ = prepared
    n = s_ops[0].shape[0]
    if n == 0:
        return jnp.asarray(0, dtype=jnp.int64)
    idx = jnp.arange(n, dtype=jnp.int64)
    diff = jnp.zeros(n, dtype=bool).at[0].set(True)
    for op in s_ops:
        diff = diff | (op != jnp.roll(op, 1))
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(diff, idx, -1))
    # dead rows share one sentinel run; exclude them via slive
    return jnp.max(jnp.where(slive, idx - start + 1, 0))


def expand_join(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str = "inner",
    max_matches: int = 1,
    prepared=None,
) -> Batch:
    """Many-to-many equi-join with static expansion factor.

    Output capacity = probe capacity * max_matches: slot k of probe row i
    holds its k-th match (masked off past the row's match count). The
    caller obtains ``max_matches`` from ``match_count_max`` (bucketed, so
    kernels recompile only when the multiplicity crosses a power of two).
    Left joins keep unmatched probe rows in slot 0 with null payload.
    """
    assert join_type in ("inner", "left")
    k = max(1, max_matches)
    prepared = prepared or build_sorted(build, build_keys)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    live = probe.row_mask & pvalid
    lo, hi = _range_lookup(q_ops, prepared)
    cnt = jnp.where(live, hi - lo, 0)

    # [k, C] grids -> flattened [k*C] output (probe-major within slots)
    slot = jnp.arange(k)[:, None]                      # [k, 1]
    pos = jnp.minimum(lo[None, :] + slot, s_ops[0].shape[0] - 1)
    # slive guards the sentinel edge (a probe key equal to int64-max would
    # otherwise "match" dead build rows)
    matched = (slot < cnt[None, :]) & jnp.take(slive, pos, axis=0)  # [k, C]

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = []
    for c in probe.columns:
        data = jnp.broadcast_to(c.data[None, :], (k,) + c.data.shape)
        valid = jnp.broadcast_to(c.validity[None, :], (k,) + c.validity.shape)
        out_cols.append(Column(c.type, data.reshape(-1), valid.reshape(-1),
                               c.dictionary))
    for ci, name in zip(payload, payload_names):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        gdata = jnp.take(sdata, pos, axis=0)           # [k, C]
        gvalid = jnp.take(svalid, pos, axis=0) & matched
        out_fields.append((name, c.type))
        out_cols.append(Column(c.type, gdata.reshape(-1), gvalid.reshape(-1),
                               c.dictionary))
    if join_type == "inner":
        mask = matched
    else:
        # unmatched probe rows survive in slot 0 with null payload
        first_slot = (slot == 0) & (cnt[None, :] == 0) & probe.row_mask
        mask = matched | first_slot
    return Batch(Schema(out_fields), out_cols, mask.reshape(-1))


def build_key_ranks(build: Batch, key_cols: Sequence[int],
                    prepared=None) -> jnp.ndarray:
    """0-based occurrence rank of each build row within its key tuple, in
    ORIGINAL row order (dead/null-key rows get 0). The executor uses this
    to slice a skewed build side into bounded-multiplicity chunks instead
    of letting expand_join's probe_capacity x max_matches output explode
    (the role of reference PositionLinks chains, which walk matches
    incrementally instead of materializing them)."""
    s_ops, slive, perm = _split_prepared(
        prepared or build_sorted(build, key_cols))
    n = s_ops[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    diff = jnp.zeros(n, dtype=bool).at[0].set(True)
    for op in s_ops:
        diff = diff | (op != jnp.roll(op, 1))
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(diff, idx, -1))
    rank_sorted = jnp.where(slive, idx - start, 0)
    return jnp.zeros(n, dtype=jnp.int64).at[perm].set(rank_sorted)


def build_match_mask(
    probe: Batch, build: Batch,
    probe_keys: Sequence[int], build_keys: Sequence[int],
    prepared=None,
) -> jnp.ndarray:
    """bool[build.capacity] in ORIGINAL build order: which build rows have
    at least one live match in this probe batch. The executor ORs these
    across probe batches to emit the unmatched-build tail of a FULL OUTER
    join (the role of reference LookupJoinOperator's OuterPositionTracker /
    LookupOuterOperator visited-positions bitmap)."""
    prepared = prepared or build_sorted(build, build_keys)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    live = probe.row_mask & pvalid
    lo, hi = _range_lookup(q_ops, prepared)
    n = s_ops[0].shape[0]
    # difference-array coverage of all [lo, hi) ranges: two scatters +
    # one scan instead of a per-match scatter
    inc = live.astype(jnp.int32)
    add = (jnp.zeros(n + 1, dtype=jnp.int32)
           .at[jnp.where(live, lo, n)].add(inc)
           .at[jnp.where(live, hi, n)].add(-inc))
    covered = (prefix_sum(add[:n]) > 0) & slive
    return jnp.zeros(n, dtype=bool).at[perm].set(covered)


def semi_join_mask(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    negated: bool = False,
    null_aware: bool = True,
    prepared=None,
) -> jnp.ndarray:
    """Membership mask for semi/anti-joins (IN / NOT IN / [NOT] EXISTS;
    reference HashSemiJoinOperator.java + SetBuilderOperator.java).

    null_aware=True (IN / NOT IN) follows ANSI IN-predicate semantics: a
    NULL probe key never matches; for NOT IN, any NULL build key makes
    membership UNKNOWN for non-matching rows (nothing passes), while an
    EMPTY build set makes NOT IN vacuously TRUE for every probe row —
    including NULL keys. null_aware=False (decorrelated [NOT] EXISTS)
    treats NULL keys as simply never equal: NOT EXISTS keeps every probe
    row without a live match.
    """
    prepared = prepared or build_sorted(build, build_keys)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    pos, hit = _point_lookup(q_ops, prepared)
    if not negated:
        return probe.row_mask & pvalid & hit
    if not null_aware:
        return probe.row_mask & ~(pvalid & hit)
    _bops, bvalid = _key_arrays(build, build_keys)
    build_has_null = jnp.any(build.row_mask & ~bvalid)
    build_empty = ~jnp.any(build.row_mask)
    anti = probe.row_mask & pvalid & ~hit & ~build_has_null
    return jnp.where(build_empty, probe.row_mask, anti)


def unique_match_build_mask(
    probe: Batch, build: Batch,
    probe_keys: Sequence[int], build_keys: Sequence[int],
    survived: jnp.ndarray,
    prepared=None,
) -> jnp.ndarray:
    """bool[build.capacity] in ORIGINAL build order: build rows whose
    unique-key match in this probe batch SURVIVED a residual predicate —
    the FULL OUTER visited-positions bitmap with a join filter applied
    (reference LookupJoinOperator's OuterPositionTracker +
    JoinFilterFunctionCompiler: a filtered-out match must not mark the
    build row as matched)."""
    prepared = prepared or build_sorted(build, build_keys)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    pos, hit = _point_lookup(q_ops, prepared)
    ok = survived & hit & probe.row_mask & pvalid
    orig = jnp.take(perm, pos, axis=0)
    n = s_ops[0].shape[0]
    return jnp.zeros(n, dtype=bool).at[
        jnp.where(ok, orig, n)].max(ok, mode="drop")


def expand_match_origins(
    probe: Batch, build: Batch,
    probe_keys: Sequence[int], build_keys: Sequence[int],
    max_matches: int,
    prepared=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(orig_build_row, matched) per expand_join output lane, flattened
    [k * probe.capacity] in the same lane order as expand_join — lets a
    residual-filtered FULL OUTER join scatter surviving lanes back onto
    original build rows for the unmatched-tail bitmap."""
    k = max(1, max_matches)
    prepared = prepared or build_sorted(build, build_keys)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    live = probe.row_mask & pvalid
    lo, hi = _range_lookup(q_ops, prepared)
    cnt = jnp.where(live, hi - lo, 0)
    slot = jnp.arange(k)[:, None]
    pos = jnp.minimum(lo[None, :] + slot, s_ops[0].shape[0] - 1)
    matched = (slot < cnt[None, :]) & jnp.take(slive, pos, axis=0)
    orig = jnp.take(perm, pos, axis=0)
    return orig.reshape(-1), matched.reshape(-1)

"""Cached jax.jit entry points for the relational kernels.

The analogue of the reference's compiled-operator caches (reference
sql/gen/PageFunctionCompiler.java:121-136 caches generated classes per
expression): each (static-args) combination compiles once, and every
batch with the same shape bucket reuses the executable. Without this the
local executor dispatches each lax primitive eagerly — per-op overhead
dominates once batches hit millions of rows.

Batch is a registered pytree whose aux data includes column types and
dictionaries, so a new dictionary tuple (rare: dictionaries are stable
per column for generator connectors) simply retraces that one call.
"""
from __future__ import annotations

import dataclasses
import functools
import threading
import time
import types as _pytypes
from typing import Optional, Sequence

import jax

from .._devtools import lockcheck as _lockcheck
from ..obs import profiler as _prof
from ..obs.metrics import REGISTRY
from ..obs.trace import TRACER
from .aggregation import AggSpec, global_aggregate, grouped_aggregate

_JIT_HITS = REGISTRY.counter("jit_cache_hits_total")
_JIT_MISSES = REGISTRY.counter("jit_cache_misses_total")
_JIT_COMPILES = REGISTRY.counter("jit_compile_total")
_JIT_COMPILE_S = REGISTRY.counter("jit_compile_seconds_total")
#: fixed-bucket histogram alongside the counter: compile-time p95
#: becomes visible on /v1/metrics (jit_compile_seconds_bucket/_sum/
#: _count) while the long-standing _total sum keeps old scrapes working
_JIT_COMPILE_HIST = REGISTRY.histogram("jit_compile_seconds")


#: sentinel: a closure captured something we cannot prove is
#: value-stable, so the program must not be shared across queries
_SIG_MISS = object()

#: recursion ceiling for closure fingerprints — deep enough for a plan
#: subtree hanging off a probe closure, cheap enough to run once per
#: program construction
_SIG_MAX_DEPTH = 32


def _value_sig(v, depth: int, seen) -> object:
    """Hashable value-identity of one captured object, or ``_SIG_MISS``.

    The contract that makes cross-query program sharing safe: two equal
    signatures mean the closures compute the SAME traced function for
    equal input avals. Only value-immutable things get a signature —
    primitives, tuples/lists of them, frozen dataclasses (the whole
    plan/expr/type system: PlanNode, ir.Expr, Type, AggSpec, Field),
    Schema, and nested pure-python functions (their code object +
    recursively-fingerprinted cells/defaults). Anything
    identity-hashable or mutable (executors, repartitioners, arrays,
    dicts) yields ``_SIG_MISS`` and the program keeps today's
    compile-per-query behavior — a miss is never wrong, only slower."""
    if depth > _SIG_MAX_DEPTH:
        return _SIG_MISS
    if v is None or v is True or v is False:
        return v
    t = type(v)
    if t in (int, float, str, bytes, complex):
        return (t.__name__, v)
    if t in (tuple, list):
        parts = tuple(_value_sig(x, depth + 1, seen) for x in v)
        if any(p is _SIG_MISS for p in parts):
            return _SIG_MISS
        return (t.__name__,) + parts
    if t is frozenset:
        parts = tuple(_value_sig(x, depth + 1, seen)
                      for x in sorted(v, key=repr))
        if any(p is _SIG_MISS for p in parts):
            return _SIG_MISS
        return ("frozenset",) + parts
    if t is _pytypes.FunctionType:
        return _fn_sig(v, depth + 1, seen)
    if t is functools.partial:
        parts = (_value_sig(v.func, depth + 1, seen),
                 _value_sig(tuple(v.args), depth + 1, seen),
                 _value_sig(tuple(sorted(v.keywords.items())),
                            depth + 1, seen))
        if any(p is _SIG_MISS for p in parts):
            return _SIG_MISS
        return ("partial",) + parts
    if t is _pytypes.BuiltinFunctionType:
        return ("bfn", getattr(v, "__module__", None), v.__qualname__)
    from ..batch import Schema
    if t is Schema:
        return ("schema", v.fields)
    if dataclasses.is_dataclass(v) and not isinstance(v, type) \
            and v.__dataclass_params__.frozen:
        parts = tuple(_value_sig(getattr(v, f.name), depth + 1, seen)
                      for f in dataclasses.fields(v))
        if any(p is _SIG_MISS for p in parts):
            return _SIG_MISS
        return ("dc", t) + parts
    return _SIG_MISS


def _fn_sig(fn, depth: int, seen) -> object:
    code = getattr(fn, "__code__", None)
    if code is None or id(fn) in seen:
        return _SIG_MISS
    seen = seen | {id(fn)}
    parts = [code]
    try:
        cells = fn.__closure__ or ()
        for cell in cells:
            parts.append(_value_sig(cell.cell_contents, depth + 1, seen))
    except ValueError:            # empty cell (still-initializing def)
        return _SIG_MISS
    for d in (fn.__defaults__ or ()):
        parts.append(_value_sig(d, depth + 1, seen))
    if any(p is _SIG_MISS for p in parts):
        return _SIG_MISS
    return ("fn",) + tuple(parts)


def program_signature(fn) -> Optional[object]:
    """Hashable cross-query identity of a program-defining closure, or
    None when it captures anything that is not provably value-stable.
    The mesh executor keys its shard_map program cache on this: the
    warm-up run of a query shape traces + compiles once, and every
    later query with the same shape dispatches the SAME executable
    instead of paying a fresh trace (the last head of the per-query
    dispatch tax after the fused exchange removed the per-round one)."""
    sig = _fn_sig(fn, 0, frozenset())
    return None if sig is _SIG_MISS else sig


class _TimedEntry:
    """Jitted callable whose FIRST invocation is timed as a compile
    (jax.jit compiles lazily on first call; later shape buckets retrace
    silently — this records the dominant first-trace cost without
    touching every dispatch). Every entry owns an ExecutableRecord in
    ``obs.profiler.EXECUTABLES``; under a profile context each dispatch
    is additionally bracketed with block_until_ready and attributed to
    the operator whose frame made the call."""

    __slots__ = ("name", "fn", "first", "_lock", "record", "donate")

    def __init__(self, name: str, fn, key=(), donate=()):
        self.name = name
        self.fn = fn
        self.first = True
        self._lock = threading.Lock()
        #: argument positions this executable DONATES (built with
        #: ``jax.jit(donate_argnums=...)``): callers must treat those
        #: inputs as consumed — the round-carried shard buffers of the
        #: fused exchange loops alias their outputs instead of churning
        #: HBM, and the donated arrays are deleted on dispatch
        self.donate = tuple(donate)
        self.record = _prof.EXECUTABLES.register(name, key)

    def __call__(self, *args):
        if _lockcheck.ENABLED:
            # an engine lock held across a device dispatch serializes
            # every other query behind this one's kernels — the runtime
            # lock validator fails the suite on it
            _lockcheck.note_dispatch(self.name)
        rec = self.record
        if rec.evicted:
            _prof.EXECUTABLES.readmit(rec)
        rec.note_invocation()
        _prof.INVOCATIONS.inc()
        if self.first:
            # one-shot flip under a lock: concurrent first calls (a
            # fixed stage starts every task at once) must count ONE
            # compile, not N
            with self._lock:
                timed, self.first = self.first, False
            if timed:
                t0 = time.perf_counter()
                with TRACER.span(f"jit-compile:{self.name}"):
                    out = self.fn(*args)
                dt = time.perf_counter() - t0
                _JIT_COMPILES.inc()
                _JIT_COMPILE_S.inc(dt)
                _JIT_COMPILE_HIST.observe(dt)
                rec.note_compile(dt, self.fn, args)
                return out
        if _prof.should_profile_call(rec):
            return _prof.profiled_call(rec, self.fn, args)
        return self.fn(*args)


def _entry_cache(name: str, factory):
    """lru_cache replacement for the jit entry points: per-(static-args)
    memo plus cache-hit/miss counters and compile spans — the metrics
    feed the reference exposes from PageFunctionCompiler's cache stats."""
    cache = {}
    lock = threading.Lock()

    def get(*key):
        fn = cache.get(key)
        if fn is None:
            with lock:
                fn = cache.get(key)
                if fn is None:
                    _JIT_MISSES.inc()
                    fn = cache[key] = _TimedEntry(name, factory(*key),
                                                  key)
                    return fn
        _JIT_HITS.inc()
        return fn
    return get


def _grouped_factory(group_indices, aggs, mode, output_capacity,
                     key_bounds, allow_dense):
    def run(batch):
        return grouped_aggregate(batch, group_indices, aggs, mode,
                                 output_capacity, allow_dense=allow_dense,
                                 key_bounds=key_bounds)
    return jax.jit(run)


_grouped = _entry_cache("grouped_aggregate", _grouped_factory)


def grouped_aggregate_jit(batch, group_indices: Sequence[int],
                          aggs: Sequence[AggSpec], mode: str = "single",
                          output_capacity: Optional[int] = None,
                          key_bounds=None, allow_dense: bool = True):
    return _grouped(tuple(group_indices), tuple(aggs), mode,
                    output_capacity,
                    tuple(key_bounds) if key_bounds else None,
                    allow_dense)(batch)


def _bounds_violation_factory(group_indices, key_bounds):
    import jax.numpy as jnp

    from ..errors import STATS_BOUND_VIOLATION

    def run(b):
        bad = jnp.zeros((), dtype=bool)
        for gi, kb in zip(group_indices, key_bounds):
            if kb is None:
                continue
            c = b.columns[gi]
            data = c.data.astype(jnp.int64)
            out = (b.row_mask & c.validity
                   & ((data < kb[0]) | (data > kb[1])))
            bad = bad | jnp.any(out)
        return jnp.where(bad, jnp.int32(STATS_BOUND_VIOLATION),
                         jnp.int32(0))
    return jax.jit(run)


_bounds_violation = _entry_cache("key_bounds_violation",
                                 _bounds_violation_factory)


def key_bounds_violation_jit(batch, group_indices, key_bounds):
    """Device scalar (error code or 0) marking live, valid group keys
    outside their stats-promised [lo, hi]. The dense composite-code
    kernel CLAMPS such keys to stay in-bounds, so the executor must
    append this scalar to its error-flag channel — the query then fails
    with STATS_BOUND_VIOLATION instead of returning misgrouped rows. No
    readback here: flags sync once per query (check_errors)."""
    return _bounds_violation(tuple(group_indices),
                             tuple(key_bounds))(batch)


def _global_factory(aggs, mode):
    def run(batch):
        return global_aggregate(batch, aggs, mode)
    return jax.jit(run)


_global = _entry_cache("global_aggregate", _global_factory)


def global_aggregate_jit(batch, aggs: Sequence[AggSpec],
                         mode: str = "single"):
    return _global(tuple(aggs), mode)(batch)


# -- join kernels ------------------------------------------------------------
# (reference: HashBuilderOperator builds one LookupSource reused by every
# probe; here prepare_build_jit sorts the build once and the probe-side
# kernels take the prepared arrays as a pytree argument)

from .join import (  # noqa: E402
    build_key_ranks, build_match_mask, expand_join, lookup_join,
    match_count_max, prepare_build, semi_join_mask,
)


_prepare = _entry_cache(
    "prepare_build",
    lambda key_cols: jax.jit(lambda b: prepare_build(b, key_cols)))


def prepare_build_jit(build, key_cols):
    return _prepare(tuple(key_cols))(build)


_lookup = _entry_cache(
    "lookup_join",
    lambda pkeys, bkeys, payload, names, jt: jax.jit(
        lambda p, b, prep: lookup_join(
            p, b, pkeys, bkeys, payload, names, jt, prepared=prep)))


def lookup_join_jit(probe, build, probe_keys, build_keys, payload,
                    payload_names, join_type, prepared):
    return _lookup(tuple(probe_keys), tuple(build_keys), tuple(payload),
                   tuple(payload_names), join_type)(probe, build, prepared)


_expand = _entry_cache(
    "expand_join",
    lambda pkeys, bkeys, payload, names, jt, max_matches: jax.jit(
        lambda p, b, prep: expand_join(
            p, b, pkeys, bkeys, payload, names, jt, max_matches,
            prepared=prep)))


def expand_join_jit(probe, build, probe_keys, build_keys, payload,
                    payload_names, join_type, max_matches, prepared):
    return _expand(tuple(probe_keys), tuple(build_keys), tuple(payload),
                   tuple(payload_names), join_type,
                   max_matches)(probe, build, prepared)


_match_count = _entry_cache(
    "match_count_max",
    lambda pkeys, bkeys: jax.jit(lambda p, b, prep: match_count_max(
        p, b, pkeys, bkeys, prepared=prep)))


def match_count_max_jit(probe, build, probe_keys, build_keys, prepared):
    return _match_count(tuple(probe_keys),
                        tuple(build_keys))(probe, build, prepared)


from .join import max_multiplicity  # noqa: E402

#: max build-key multiplicity of a prepared build — ONE readback per
#: build, replacing the per-probe-batch match_count_max syncs for
#: non-skewed builds (jit retraces per prepared-pytree structure, so one
#: wrapper covers both the direct and sorted layouts)
max_multiplicity_jit = _TimedEntry("max_multiplicity",
                                   jax.jit(max_multiplicity))


_match_mask = _entry_cache(
    "build_match_mask",
    lambda pkeys, bkeys: jax.jit(lambda p, b, prep: build_match_mask(
        p, b, pkeys, bkeys, prepared=prep)))


def build_match_mask_jit(probe, build, probe_keys, build_keys, prepared):
    return _match_mask(tuple(probe_keys),
                       tuple(build_keys))(probe, build, prepared)


_key_ranks = _entry_cache(
    "build_key_ranks",
    lambda key_cols: jax.jit(lambda b, prep: build_key_ranks(
        b, key_cols, prepared=prep)))


def build_key_ranks_jit(build, key_cols, prepared):
    return _key_ranks(tuple(key_cols))(build, prepared)


_semi = _entry_cache(
    "semi_join_mask",
    lambda skeys, fkeys, negated, null_aware: jax.jit(
        lambda p, b, prep: semi_join_mask(
            p, b, skeys, fkeys, negated, null_aware, prepared=prep)))


def semi_join_mask_jit(probe, build, probe_keys, build_keys,
                       negated, null_aware, prepared):
    return _semi(tuple(probe_keys), tuple(build_keys), negated,
                 null_aware)(probe, build, prepared)


_compact = _entry_cache(
    "compact",
    lambda capacity: jax.jit(lambda b: b.compact(capacity, check=False)))


def compact_jit(batch, capacity: int):
    """Jitted Batch.compact — shrink a sparse batch to a bucketed
    capacity (callers must know the live count fits)."""
    return _compact(capacity)(batch)


_pad = _entry_cache(
    "pad_capacity",
    lambda capacity: jax.jit(lambda b: b.pad(capacity)))


def pad_capacity_jit(batch, capacity: int):
    """Jitted Batch.pad — grow a ragged batch (a split's residual final
    chunk) to the scan stream's standard bucket with dead lanes, so
    downstream operators reuse one executable per shape instead of
    compiling one per residual size."""
    return _pad(capacity)(batch)


from .join import prepare_direct  # noqa: E402


_prepare_direct = _entry_cache(
    "prepare_direct",
    lambda key_cols, size: jax.jit(
        lambda b, lo0: prepare_direct(b, key_cols, lo0, size)))


def prepare_direct_jit(build, key_cols, lo0, size: int):
    return _prepare_direct(tuple(key_cols), size)(build, lo0)


from .join import prepare_direct_keyed  # noqa: E402


_prepare_direct_keyed = _entry_cache(
    "prepare_direct_keyed",
    lambda key_cols, los, sizes, size: jax.jit(
        lambda b: prepare_direct_keyed(b, key_cols, los, sizes, size)))


def prepare_direct_keyed_jit(build, key_cols, los, sizes, size: int):
    """Planner-bounded multi-key direct table: los/sizes/size are
    host-static (from JoinNode.key_bounds), so the table capacity — and
    every probe executable shape over it — is known at plan time."""
    return _prepare_direct_keyed(tuple(key_cols), tuple(los),
                                 tuple(sizes), size)(build)


def _lookup_pallas_factory(pkeys, bkeys, payload, names, jt):
    from .pallas_join import lookup_join_direct

    def run(p, b, prep):
        return lookup_join_direct(p, b, pkeys, bkeys, payload, names,
                                  jt, prep)
    return jax.jit(run)


_lookup_pallas = _entry_cache("lookup_join_pallas", _lookup_pallas_factory)


def lookup_join_pallas_jit(probe, build, probe_keys, build_keys, payload,
                           payload_names, join_type, prepared):
    """The Pallas probe-kernel twin of lookup_join_jit (direct prepared
    only — callers gate on ops/pallas_join.supports_join and fall back
    to the XLA path on any kernel failure)."""
    return _lookup_pallas(tuple(probe_keys), tuple(build_keys),
                          tuple(payload), tuple(payload_names),
                          join_type)(probe, build, prepared)


def _build_summary_factory(key_cols, int_flags):
    import jax.numpy as jnp

    def run(b):
        live = b.row_mask
        out = [jnp.sum(live.astype(jnp.int64))]
        for k, is_int in zip(key_cols, int_flags):
            if not is_int:
                out += [jnp.int64(0), jnp.int64(-1)]
                continue
            c = b.columns[k]
            ok = live & c.validity
            data = c.data.astype(jnp.int64)
            out.append(jnp.min(jnp.where(ok, data,
                                         jnp.iinfo(jnp.int64).max)))
            out.append(jnp.max(jnp.where(ok, data,
                                         jnp.iinfo(jnp.int64).min)))
        return jnp.stack(out)
    return jax.jit(run)


_build_summary = _entry_cache("build_summary", _build_summary_factory)


def build_summary_jit(build, key_cols, int_flags):
    """One fused device reduction for everything the executor needs to
    know about a drained join build: [live_count, (lo, hi) per key].
    Non-integer keys report (0, -1). The caller reads it back ONCE — on
    the tunneled backend every separate readback costs a full RTT plus a
    flush of queued async work, and the previous code paid three (live
    count, direct-table bounds, dynamic-filter bounds)."""
    return _build_summary(tuple(key_cols), tuple(int_flags))(build)


from .join import expand_match_origins, unique_match_build_mask  # noqa: E402


_unique_match_build = _entry_cache(
    "unique_match_build_mask",
    lambda pkeys, bkeys: jax.jit(
        lambda p, b, s, prep: unique_match_build_mask(
            p, b, pkeys, bkeys, s, prepared=prep)))


def unique_match_build_mask_jit(probe, build, probe_keys, build_keys,
                                survived, prepared):
    return _unique_match_build(tuple(probe_keys), tuple(build_keys))(
        probe, build, survived, prepared)


_expand_origins = _entry_cache(
    "expand_match_origins",
    lambda pkeys, bkeys, k: jax.jit(
        lambda p, b, prep: expand_match_origins(
            p, b, pkeys, bkeys, k, prepared=prep)))


def expand_match_origins_jit(probe, build, probe_keys, build_keys,
                             max_matches, prepared):
    return _expand_origins(tuple(probe_keys), tuple(build_keys),
                           max_matches)(probe, build, prepared)

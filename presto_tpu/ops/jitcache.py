"""Cached jax.jit entry points for the relational kernels.

The analogue of the reference's compiled-operator caches (reference
sql/gen/PageFunctionCompiler.java:121-136 caches generated classes per
expression): each (static-args) combination compiles once, and every
batch with the same shape bucket reuses the executable. Without this the
local executor dispatches each lax primitive eagerly — per-op overhead
dominates once batches hit millions of rows.

Batch is a registered pytree whose aux data includes column types and
dictionaries, so a new dictionary tuple (rare: dictionaries are stable
per column for generator connectors) simply retraces that one call.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax

from .aggregation import AggSpec, global_aggregate, grouped_aggregate


@functools.lru_cache(maxsize=None)
def _grouped(group_indices, aggs, mode, output_capacity):
    def run(batch):
        return grouped_aggregate(batch, group_indices, aggs, mode,
                                 output_capacity)
    return jax.jit(run)


def grouped_aggregate_jit(batch, group_indices: Sequence[int],
                          aggs: Sequence[AggSpec], mode: str = "single",
                          output_capacity: Optional[int] = None):
    return _grouped(tuple(group_indices), tuple(aggs), mode,
                    output_capacity)(batch)


@functools.lru_cache(maxsize=None)
def _global(aggs, mode):
    def run(batch):
        return global_aggregate(batch, aggs, mode)
    return jax.jit(run)


def global_aggregate_jit(batch, aggs: Sequence[AggSpec],
                         mode: str = "single"):
    return _global(tuple(aggs), mode)(batch)

"""Sort / TopN / Limit kernels.

The TPU-native replacement for Presto's PagesIndex sort + OrderByOperator /
TopNOperator (reference presto-main/.../operator/PagesIndex.java,
OrderByOperator.java, TopNOperator.java): instead of an index of row
addresses ordered by a generated comparator, we run ``jax.lax.sort`` with
multiple key operands (lexicographic), which XLA lowers to an efficient
on-device sort. Dead rows always sort to the end; null ordering follows
Presto defaults (NULLS LAST for ASC, NULLS FIRST for DESC,
reference sql/tree/SortItem.java NullOrdering).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .prefix import prefix_sum
from .. import types as T
from ..batch import Batch, Column, Schema


@dataclasses.dataclass(frozen=True)
class SortKey:
    column: int
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = Presto default

    def effective_nulls_first(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return not self.ascending


def _rank_table(vocab: Tuple[str, ...]) -> jnp.ndarray:
    """Order-preserving rank for dictionary codes (+ sentinel slot)."""
    order = np.argsort(np.argsort(np.asarray(vocab, dtype=object)))
    table = np.empty(len(vocab) + 1, dtype=np.int64)
    table[:len(vocab)] = order
    table[-1] = -1
    return jnp.asarray(table)


def rank_codes(data: jnp.ndarray, vocab: Optional[Tuple[str, ...]]) -> jnp.ndarray:
    """Map dictionary codes to lexicographic ranks (negative codes -> -1)."""
    table = _rank_table(vocab or ())
    idx = jnp.where(data >= 0, data, table.shape[0] - 1)
    return jnp.take(table, idx, axis=0)


def unrank_table(vocab: Optional[Tuple[str, ...]]) -> jnp.ndarray:
    """Inverse of _rank_table: rank -> dictionary code."""
    order = (np.argsort(np.asarray(vocab, dtype=object))
             if vocab else np.zeros(1))
    return jnp.asarray(order.astype(np.int64))


def _sortable(col: Column, key: SortKey) -> List[jnp.ndarray]:
    """Transform one column into ascending-sortable operand(s):
    [null_rank, data'] where smaller sorts first."""
    data = col.data
    nulls_first = key.effective_nulls_first()
    null_rank = jnp.where(col.validity, 1, 0) if nulls_first else jnp.where(col.validity, 0, 1)
    if getattr(data, "ndim", 1) == 2:
        # long-decimal limb pairs: two operands (hi, unsigned-ordered lo)
        from . import int128 as I
        h, l = I.hi(data), I.sortable_lo(data)
        if not key.ascending:
            h, l = ~h, ~l
        h = jnp.where(col.validity, h, jnp.zeros_like(h))
        l = jnp.where(col.validity, l, jnp.zeros_like(l))
        return [null_rank.astype(jnp.int32), h, l]
    if col.type.is_string:
        data = rank_codes(data, col.dictionary)
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int32)
    if not key.ascending:
        if jnp.issubdtype(data.dtype, jnp.floating):
            data = -data
        else:
            # avoid INT_MIN overflow: flip bits instead of negating
            data = ~data
    # NULL rows tie on null_rank; neutralize their data operand so stale
    # values never order two NULLs differently from each other's payload
    data = jnp.where(col.validity, data, jnp.zeros_like(data))
    return [null_rank.astype(jnp.int32), data]


def sort_permutation(batch: Batch, keys: Sequence[SortKey]) -> jnp.ndarray:
    """Stable sort permutation of rows by keys; dead rows sort last.

    Only key operands plus a row index enter ``lax.sort`` — TPU
    variadic-sort compile time grows superlinearly with operand count
    (measured ~215s cold for 10 operands vs ~20s for keys+iota on v5e),
    so payloads are always gathered by the permutation instead."""
    dead_rank = jnp.where(batch.row_mask, 0, 1).astype(jnp.int32)
    operands = [dead_rank]
    for k in keys:
        operands.extend(_sortable(batch.columns[k.column], k))
    idx = jnp.arange(batch.capacity, dtype=jnp.int32)
    out = jax.lax.sort(operands + [idx], num_keys=len(operands),
                       is_stable=True)
    return out[-1]


def permute_batch(batch: Batch, perm: jnp.ndarray) -> Batch:
    """Gather every row-aligned array of a batch by ``perm``."""
    cols = [Column(c.type,
                   jax.tree_util.tree_map(
                       lambda a: jnp.take(a, perm, axis=0), c.data),
                   jnp.take(c.validity, perm, axis=0), c.dictionary)
            for c in batch.columns]
    return Batch(batch.schema, cols, jnp.take(batch.row_mask, perm, axis=0))


def sort_batch(batch: Batch, keys: Sequence[SortKey]) -> Batch:
    """Stable sort of live rows by keys; dead rows go to the end."""
    return permute_batch(batch, sort_permutation(batch, keys))


def limit(batch: Batch, n: int) -> Batch:
    """Keep the first n live rows (in current physical order)."""
    live_rank = prefix_sum(batch.row_mask.astype(jnp.int64))
    keep = batch.row_mask & (live_rank <= n)
    return Batch(batch.schema, batch.columns, keep)


def top_n(batch: Batch, keys: Sequence[SortKey], n: int) -> Batch:
    """ORDER BY ... LIMIT n (reference TopNOperator.java). Full device sort
    then mask; a partial top-k path is a later optimization."""
    return limit(sort_batch(batch, keys), n)

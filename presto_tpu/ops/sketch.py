"""Sketch kernels: HyperLogLog (approx_distinct) and a log-linear
quantile histogram (approx_percentile).

The TPU-native reshape of the reference's HLL aggregation state
(reference presto-main/.../operator/aggregation/
ApproximateCountDistinctAggregations.java + state/HyperLogLogState.java,
backed by airlift's HyperLogLog): per group, m = (1.04/e)^2 registers
each holding the max leading-zero rank of hashed inputs in that bucket.

Device shape: registers live in a dense i32 tile [groups, m] — updates
are ONE segment_max over flattened (group, bucket) slots, merges are ONE
segment_max over rows of state tiles, and estimation is a vectorized
harmonic mean. No per-row control flow, no sparse representation: the
engine only routes approx_distinct through this path when the group
count is statically bounded (dictionary/bool keys or a global
aggregate), so the dense tile is small; unbounded group-bys keep the
exact sort-based fallback (which is EXACT — a strictly tighter error
bound than the reference's sketch on that shape).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .prefix import prefix_sum

#: default standard error of the reference's approx_distinct (reference
#: ApproximateCountDistinctAggregations.java DEFAULT_STANDARD_ERROR)
DEFAULT_STANDARD_ERROR = 0.023
MIN_STANDARD_ERROR = 0.0040625
MAX_STANDARD_ERROR = 0.26


def hll_m(error: Optional[float]) -> int:
    """Register count for a target standard error (1.04/sqrt(m)),
    rounded up to a power of two like the reference's bucket counts."""
    e = DEFAULT_STANDARD_ERROR if error is None else float(error)
    if not (MIN_STANDARD_ERROR <= e <= MAX_STANDARD_ERROR):
        raise ValueError(
            f"standard error must be in [{MIN_STANDARD_ERROR}, "
            f"{MAX_STANDARD_ERROR}]: {e}")
    m = int(math.ceil((1.04 / e) ** 2))
    return 1 << max(int(math.ceil(math.log2(m))), 4)


def splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """Stateless 64-bit mix (the device-friendly stand-in for the
    reference's Murmur3 element hashing): good avalanche, pure vector
    ops."""
    x = x.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def hash_dictionary(vocab: Tuple[str, ...]) -> np.ndarray:
    """Stable 64-bit hashes of a string vocabulary (host-side, gathered
    by code on device): dictionary codes are per-batch, so hashing the
    VALUES keeps sketches mergeable across batches and shards."""
    import zlib
    out = np.empty(max(len(vocab), 1), dtype=np.uint64)
    out[:] = 1
    for i, s in enumerate(vocab):
        b = s.encode("utf-8")
        # two independent crcs widen to 64 bits; splitmix on device
        # finalizes, so only distinctness matters here
        out[i] = (np.uint64(zlib.crc32(b)) << np.uint64(32)) \
            | np.uint64(zlib.crc32(b, 0x9E3779B9))
    return out


def bucket_and_rank(hashed: jnp.ndarray, m: int):
    """(bucket, rank): bucket = top log2(m) bits, rank = leading-zero
    count of the remaining bits + 1 (the classic HLL decomposition)."""
    b = int(math.log2(m))
    h = hashed.astype(jnp.uint64)
    bucket = (h >> jnp.uint64(64 - b)).astype(jnp.int32)
    # the sentinel bit guarantees a nonzero word, capping the rank at
    # 64 - b + 1 like the reference's value-bit budget
    rest = (h << jnp.uint64(b)) | (jnp.uint64(1) << jnp.uint64(b - 1))
    rank = (jax.lax.clz(rest).astype(jnp.int32) + 1)
    return bucket, rank


def hll_update(group_slot: jnp.ndarray, valid: jnp.ndarray,
               hashed: jnp.ndarray, cap: int, m: int) -> jnp.ndarray:
    """Registers [cap, m] from one pass of hashed values: segment_max
    over flattened (group, bucket) slots; invalid rows rank 0."""
    bucket, rank = bucket_and_rank(hashed, m)
    flat = group_slot.astype(jnp.int64) * m + bucket
    flat = jnp.where(valid, flat, cap * m)      # dead rows -> trash slot
    ranks = jnp.where(valid, rank, 0)
    regs = jax.ops.segment_max(ranks, flat.astype(jnp.int32),
                               num_segments=cap * m + 1)
    return jnp.maximum(regs[:cap * m], 0).reshape(cap, m)


def hll_estimate(registers: jnp.ndarray) -> jnp.ndarray:
    """Bias-corrected cardinality per group from registers [..., m]
    (the standard HLL estimator with the linear-counting small-range
    correction the reference applies)."""
    m = registers.shape[-1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    regs = registers.astype(jnp.float64)
    raw = alpha * m * m / jnp.sum(jnp.power(2.0, -regs), axis=-1)
    zeros = jnp.sum((registers == 0).astype(jnp.float64), axis=-1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    small = raw <= 2.5 * m
    est = jnp.where(small & (zeros > 0), linear, raw)
    return jnp.round(est).astype(jnp.int64)


# ---------------------------------------------------------------------------
# Quantile histogram (approx_percentile)
#
# The TPU-native reshape of the reference's QuantileDigest state
# (reference presto-main/.../operator/aggregation/state/
# DigestAndPercentileState.java + airlift QuantileDigest): instead of a
# sparse adaptive tree over the i64 universe, a DENSE log-linear
# histogram — QD_L linear sub-buckets per power of two, covering the
# full exponent range device doubles support (f64 on this chip is a
# double-float emulation with f32 exponent range, so e in [-126, 128)
# covers every representable magnitude; CPU-side values beyond that
# clamp into the edge bins). Counts are one i32 scatter per batch,
# merges are one vector add, estimation is one cumsum + argmax — all
# static-shape, and the state is a fixed [QD_BINS] i64 tile regardless
# of input size, which is the whole point of the sketch: bounded,
# mergeable partial state across exchanges.
#
# Error bound: a value in bin (e, sub) lies within the bin's value
# span, whose relative width is 1/(QD_L + sub) <= 1/QD_L; reporting the
# bin midpoint bounds the relative error by 1/(2*QD_L) (~1.6% at
# QD_L=32) — the value-space analogue of the reference qdigest's 1%
# rank-error default. Exact zero (and subnormals) get a dedicated bin.
# ---------------------------------------------------------------------------

QD_L = 32                      # linear sub-buckets per octave
QD_E_LO = -126                 # lowest exponent bin (f32-range doubles)
QD_E_COUNT = 254               # exponents -126 .. 127
QD_P = QD_E_COUNT * QD_L       # magnitude bins per sign
QD_BINS = 2 * QD_P + 1         # negatives desc | zero | positives asc


def qd_bin(values: jnp.ndarray) -> jnp.ndarray:
    """Bin index in ascending VALUE order for f64 inputs: negatives
    mirror below the zero bin, positives above it."""
    av = jnp.abs(values)
    nan = jnp.isnan(values)
    tiny = (av < 2.0 ** QD_E_LO) & ~nan     # 0 and subnormal-ish
    e = jnp.floor(jnp.log2(jnp.where(tiny | nan, 1.0, av)))
    e = jnp.clip(e, QD_E_LO, QD_E_LO + QD_E_COUNT - 1)
    m = av * jnp.exp2(-e)
    sub = jnp.clip(jnp.floor((m - 1.0) * QD_L).astype(jnp.int32),
                   0, QD_L - 1)
    mag = (e.astype(jnp.int32) - QD_E_LO) * QD_L + sub
    idx = jnp.where(values >= 0, QD_P + 1 + mag, QD_P - 1 - mag)
    idx = jnp.where(tiny, QD_P, idx)
    # NaN sorts after every number in the exact segmented-sort path, so
    # the sketch keeps it in the top bin for the same rank behavior
    return jnp.where(nan, QD_BINS - 1, idx).astype(jnp.int32)


def qd_update(valid: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """[QD_BINS] i64 bin counts from one pass of raw values (one i32
    scatter-add; dead rows land in a trash slot past the tile)."""
    idx = jnp.where(valid, qd_bin(values.astype(jnp.float64)), QD_BINS)
    ones = jnp.ones(values.shape, dtype=jnp.int32)
    counts = jax.ops.segment_sum(ones, idx, num_segments=QD_BINS + 1)
    return counts[:QD_BINS].astype(jnp.int64)


def qd_rep_values() -> np.ndarray:
    """Static [QD_BINS] table of bin representative values (midpoints in
    the linear sub-bucket; exact 0.0 for the zero bin)."""
    mag = np.arange(QD_P)
    e = (QD_E_LO + mag // QD_L).astype(np.float64)
    sub = mag % QD_L
    m = 1.0 + (sub + 0.5) / QD_L
    pos = np.exp2(e) * m
    return np.concatenate([-pos[::-1], np.zeros(1), pos])


def qd_estimate(counts: jnp.ndarray, p: float):
    """Nearest-rank percentile over counts [..., QD_BINS]: cumulative
    counts cross ceil(p*n) in exactly the bin holding the exact
    nearest-rank element, so the only error is the within-bin midpoint
    snap. Returns (value f64, valid)."""
    total = jnp.sum(counts, axis=-1)
    k = jnp.clip(jnp.ceil(p * total.astype(jnp.float64)).astype(jnp.int64),
                 1, jnp.maximum(total, 1))
    cum = prefix_sum(counts, axis=counts.ndim - 1)
    bin_idx = jnp.argmax(cum >= k[..., None], axis=-1)
    reps = jnp.asarray(qd_rep_values())
    return jnp.take(reps, bin_idx, axis=0), total > 0


def hashed_column(data: jnp.ndarray, dictionary) -> jnp.ndarray:
    """Device hash of a column's storage values: strings hash their
    dictionary VALUES (host-stable) gathered by code; numerics hash
    their storage bits."""
    if dictionary is not None:
        table = jnp.asarray(hash_dictionary(tuple(dictionary)))
        codes = jnp.clip(data.astype(jnp.int32), 0, table.shape[0] - 1)
        return splitmix64(jnp.take(table, codes, axis=0).astype(jnp.int64))
    if getattr(data, "ndim", 1) == 2:
        # long-decimal limb pairs: chain both limbs through the mixer
        return splitmix64(data[..., 0] ^
                          splitmix64(data[..., 1]).astype(jnp.int64))
    if data.dtype == jnp.bool_:
        return splitmix64(data.astype(jnp.int64))
    if jnp.issubdtype(data.dtype, jnp.floating):
        # canonicalize -0.0 so equal SQL values hash equally
        canon = jnp.where(data == 0, jnp.zeros_like(data), data)
        bits = jax.lax.bitcast_convert_type(
            canon.astype(jnp.float64), jnp.int64)
        return splitmix64(bits)
    return splitmix64(data.astype(jnp.int64))

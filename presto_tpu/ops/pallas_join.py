"""Pallas TPU probe kernel for direct-address joins: one tiled pass
fusing probe-slot lookup + liveness mask + payload gather.

Why this exists (docs/perf.md round 8): the XLA probe path for a
direct-address join emits one separate gather op per table/payload
column — each materializes its [probe_capacity] output in HBM, so an
N-payload dimension join re-reads the probe-sized index vector N+2
times and re-writes N+2 full-width intermediates per batch. This kernel
makes the probe inner loop ONE grid pass: a [R, L] tile of probe slot
codes is resolved against the VMEM-resident lookup tables (lo/cnt: the
TWO gathers `ops/join.prepare_direct` promises), the match count and a
packed validity bitmask come back with it, and every payload plane is
gathered in the same tile visit — no per-gather HBM round trips. The
ragged-gather shape follows the Ragged Paged Attention exemplar
(PAPERS.md): fixed tile grid over a ragged logical access pattern, with
the page table (here: lo/cnt tables) resident on-chip.

Backend constraints that shape this file (same as ops/pallas_scan.py):

- the tunneled backend rewrites all X64 types and cannot rewrite custom
  calls, so NO 64-bit array may cross the ``pallas_call`` boundary.
  64-bit payloads (bigint, double via IEEE bitcast, int128 limb pairs)
  decompose into two i32 digit planes OUTSIDE the kernel and are
  reassembled from the gathered planes — truncating i64->i32 casts are
  exact mod 2^32, so ``(hi << 32) | (lo & 0xffffffff)`` round-trips
  every value;
- per-column validity masks pack into ONE i32 bit-plane (bit c =
  payload column c), so a join gathers validity for up to 32 payload
  columns in a single extra plane;
- tables and payload planes must fit VMEM (~16MB/core): the dispatch
  gate ``direct_probe_supported`` budgets them and falls back to the
  XLA path above the budget — exactly the dimension-table sizes the
  direct path targets fit, fact-table builds never take it.

The kernel is semantics-preserving against ``ops/join.lookup_join`` on
a direct prepared (asserted row-exact by tests/test_join_strategy.py in
interpret mode). Engine call sites keep a pure-XLA fallback behind the
``join_pallas_probe`` session property, and the FIRST kernel dispatch
failing to compile flips a process-wide breaker so the query (and every
later one) transparently re-runs on XLA — an unproven Mosaic lowering
can cost one failed compile, never a wrong or failed query.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..batch import Batch, Column, Schema
from ..obs.metrics import REGISTRY
from .join import _key_arrays, direct_slot_codes, is_direct_prepared, \
    _split_prepared

R, L = 8, 128            # probe tile: 8 sublanes x 128 lanes
TILE = R * L

#: VMEM budget for tables + payload planes (out of ~16MB/core); above
#: it the dispatch gate declines and the XLA path runs
VMEM_BUDGET_BYTES = 8 << 20

#: tests set this to exercise the kernel on the CPU mesh (interpret
#: mode); engine call sites otherwise use it only on real TPU backends
FORCE_PALLAS_PROBE = False

_FALLBACKS = REGISTRY.counter("join_pallas_fallback_total")

#: process-wide breaker: the first dispatch whose Mosaic lowering fails
#: flips it, and every later dispatch goes straight to the XLA path
_STATE = {"broken": False}


def _interpret() -> bool:
    return jax.default_backend() in ("cpu",)


def kernel_enabled() -> bool:
    """Backend supports the kernel and it has not tripped the breaker."""
    if _STATE["broken"]:
        return False
    return FORCE_PALLAS_PROBE or jax.default_backend() not in ("cpu",)


def note_kernel_failure(exc: BaseException) -> None:
    """First-compile failure: trip the breaker (process-wide) so every
    later dispatch takes the XLA path without retrying the compile."""
    _STATE["broken"] = True
    _FALLBACKS.inc()
    from ..obs.log import LOG
    LOG.log("pallas_probe_disabled",
            error=f"{type(exc).__name__}: {exc}")


def _planes_for(data) -> int:
    if getattr(data, "ndim", 1) == 2:
        return 4
    if data.dtype in (jnp.float64, jnp.int64, jnp.uint64):
        return 2
    return 1


def supports_join(prepared, build: Batch, payload: Sequence[int]) -> bool:
    """Full dispatch gate for one lookup join: direct prepared, packable
    validity bits (<= 31 payload columns), and everything within the
    VMEM budget. Host-static under jit (reads dtypes/shapes only)."""
    if not kernel_enabled() or not is_direct_prepared(prepared):
        return False
    if len(payload) > 31:
        return False
    n_planes = sum(_planes_for(build.columns[ci].data) for ci in payload)
    return direct_probe_supported(prepared, n_planes)


def direct_probe_supported(prepared, n_planes: int) -> bool:
    """VMEM budget gate: both lookup tables, the validity bit-plane and
    every payload plane must be resident on-chip for the fused pass."""
    if not is_direct_prepared(prepared):
        return False
    lo_table = prepared[1] if len(prepared) == 6 else prepared[2]
    s_ops = _split_prepared(prepared)[0]
    n_build = int(s_ops[0].shape[0])
    size = int(lo_table.shape[0])
    if size < L or n_build < L:
        return False            # tables pad to lane width; tiny builds
    bytes_needed = 4 * (2 * size + (1 + n_planes) * n_build + 2 * TILE)
    return bytes_needed <= VMEM_BUDGET_BYTES


# ---------------------------------------------------------------------------
# i32 plane decomposition (outside the kernel; see module docstring)
# ---------------------------------------------------------------------------

_M32 = jnp.int64(0xFFFFFFFF)


def _decompose(data: jnp.ndarray) -> Tuple[str, List[jnp.ndarray]]:
    """(tag, i32 planes) of one payload column's device array."""
    if getattr(data, "ndim", 1) == 2:
        # int128 limb pairs [n, 2] of i64: four digit planes
        planes = []
        for limb in (data[..., 0], data[..., 1]):
            planes.append((limb >> jnp.int64(32)).astype(jnp.int32))
            planes.append((limb & _M32).astype(jnp.int32))
        return "i128", planes
    dt = data.dtype
    if dt == jnp.float64:
        u = jax.lax.bitcast_convert_type(data, jnp.uint64)
        s = u.astype(jnp.int64)
        return "f64", [(s >> jnp.int64(32)).astype(jnp.int32),
                       (s & _M32).astype(jnp.int32)]
    if dt in (jnp.int64, jnp.uint64):
        s = data.astype(jnp.int64)
        tag = "i64" if dt == jnp.int64 else "u64"
        return tag, [(s >> jnp.int64(32)).astype(jnp.int32),
                     (s & _M32).astype(jnp.int32)]
    if dt == jnp.float32:
        return "f32", [jax.lax.bitcast_convert_type(data, jnp.int32)]
    if dt == jnp.bool_:
        return "bool", [data.astype(jnp.int32)]
    # int32 / int16 / int8 / date codes / dictionary codes
    return str(dt), [data.astype(jnp.int32)]


def _reassemble(tag: str, planes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    def to64(hi, lo):
        return ((hi.astype(jnp.int64) << jnp.int64(32))
                | (lo.astype(jnp.int64) & _M32))
    if tag == "i128":
        return jnp.stack([to64(planes[0], planes[1]),
                          to64(planes[2], planes[3])], axis=-1)
    if tag == "f64":
        return jax.lax.bitcast_convert_type(
            to64(planes[0], planes[1]).astype(jnp.uint64), jnp.float64)
    if tag == "i64":
        return to64(planes[0], planes[1])
    if tag == "u64":
        return to64(planes[0], planes[1]).astype(jnp.uint64)
    if tag == "f32":
        return jax.lax.bitcast_convert_type(planes[0], jnp.float32)
    if tag == "bool":
        return planes[0].astype(jnp.bool_)
    return planes[0].astype(jnp.dtype(tag))


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _imap(i):
    # literal indices pinned to i32 (Mosaic rejects i64 at func.return
    # under jax_enable_x64 — same guard as ops/pallas_scan._imap)
    return (jnp.asarray(i, jnp.int32), jnp.int32(0))


def _full(i):
    return (jnp.int32(0), jnp.int32(0))


def _probe_kernel_factory(n_planes: int, n_build: int):
    def kernel(code_ref, lo_ref, cnt_ref, vb_ref, *refs):
        plane_refs = refs[:n_planes]
        cnt_out, vb_out = refs[n_planes], refs[n_planes + 1]
        outs = refs[n_planes + 2:]
        idx = code_ref[:]                        # [R, L]; -1 = no-lookup
        ok = idx >= 0
        safe = jnp.where(ok, idx, 0)
        lo = jnp.take(lo_ref[0, :], safe, axis=0)
        cnt = jnp.where(ok, jnp.take(cnt_ref[0, :], safe, axis=0), 0)
        cnt_out[:] = cnt
        pos = jnp.clip(lo, 0, n_build - 1)
        hit = cnt > 0
        vb_out[:] = jnp.where(hit, jnp.take(vb_ref[0, :], pos, axis=0), 0)
        for p in range(n_planes):
            outs[p][:] = jnp.take(plane_refs[p][0, :], pos, axis=0)
    return kernel


def _direct_probe_call(codes2d, lo_t, cnt_t, vbits, planes,
                       interpret: bool):
    from jax.experimental import pallas as pl
    n_planes = len(planes)
    n_build = planes[0].shape[1] if planes else vbits.shape[1]
    rows = codes2d.shape[0]
    tile = pl.BlockSpec((R, L), _imap)
    res = pl.BlockSpec((1, lo_t.shape[1]), _full)
    pres = pl.BlockSpec((1, n_build), _full)
    out_shapes = ([jax.ShapeDtypeStruct(codes2d.shape, jnp.int32)] * 2
                  + [jax.ShapeDtypeStruct(codes2d.shape, jnp.int32)
                     for _ in range(n_planes)])
    out = pl.pallas_call(
        _probe_kernel_factory(n_planes, n_build),
        grid=(rows // R,),
        in_specs=[tile, res, res, pres] + [pres] * n_planes,
        out_specs=[tile, tile] + [tile] * n_planes,
        out_shape=out_shapes,
        interpret=interpret,
    )(codes2d, lo_t, cnt_t, vbits, *planes)
    return out[0], out[1], out[2:]


def direct_probe(codes: jnp.ndarray, lo_table: jnp.ndarray,
                 cnt_table: jnp.ndarray, vbits: jnp.ndarray,
                 planes: Sequence[jnp.ndarray], interpret=None):
    """(cnt, vbits_gathered, payload planes gathered) per probe lane.

    ``codes``: i32[n] slot indices, -1 for lanes that must not match
    (out of domain / NULL key / dead row). ``vbits``/``planes``:
    i32[n_build] arrays in SORTED build order. All i32 in and out — the
    64-bit decomposition happens in the caller (module docstring)."""
    if interpret is None:
        interpret = _interpret()
    n = codes.shape[0]
    pad = (-n) % TILE
    if pad:
        codes = jnp.concatenate(
            [codes, jnp.full(pad, -1, dtype=jnp.int32)])
    codes2d = codes.reshape(-1, L)
    cnt2d, vb2d, out2d = _direct_probe_call(
        codes2d, lo_table.reshape(1, -1), cnt_table.reshape(1, -1),
        vbits.reshape(1, -1), [p.reshape(1, -1) for p in planes],
        interpret)
    unpad = lambda a: a.reshape(-1)[:n]
    return unpad(cnt2d), unpad(vb2d), [unpad(o) for o in out2d]


# ---------------------------------------------------------------------------
# lookup_join on the kernel (the fused probe inner loop)
# ---------------------------------------------------------------------------

def lookup_join_direct(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str,
    prepared,
) -> Batch:
    """``ops/join.lookup_join`` semantics on the Pallas probe kernel —
    unique-build inner/left join against a direct prepared. Row-exact
    with the XLA path by construction: the same ``direct_slot_codes``
    addressing, the same clip/mask semantics, only the gather engine
    differs."""
    assert join_type in ("inner", "left")
    assert is_direct_prepared(prepared)
    s_ops, slive, perm = _split_prepared(prepared)
    q_ops, pvalid = _key_arrays(probe, probe_keys)
    slot, inr = direct_slot_codes(q_ops, prepared)
    live = probe.row_mask & pvalid & inr
    codes = jnp.where(live, slot, -1).astype(jnp.int32)

    # sorted-order payload planes + packed validity bits (32 cols/plane)
    tags: List[Tuple[str, int]] = []
    planes: List[jnp.ndarray] = []
    vbits = jnp.zeros(slive.shape, dtype=jnp.int32)
    for c_i, ci in enumerate(payload):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        tag, ps = _decompose(sdata)
        tags.append((tag, len(ps)))
        planes.extend(ps)
        vbits = vbits | (svalid.astype(jnp.int32) << c_i)

    cnt, vb, gathered = direct_probe(codes, prepared[1] if
                                     len(prepared) == 6 else prepared[2],
                                     prepared[2] if len(prepared) == 6
                                     else prepared[3], vbits, planes)
    match = cnt > 0            # codes already folded row_mask/valid/inr

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = list(probe.columns)
    at = 0
    for j, ((tag, k), ci, name) in enumerate(zip(tags, payload,
                                                 payload_names)):
        c = build.columns[ci]
        data = _reassemble(tag, gathered[at:at + k])
        at += k
        valid = (((vb >> j) & 1) > 0) & match
        out_fields.append((name, c.type))
        out_cols.append(Column(c.type, data, valid, c.dictionary))
    mask = match if join_type == "inner" else probe.row_mask
    return Batch(Schema(out_fields), out_cols, mask)

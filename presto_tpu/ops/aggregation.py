"""Aggregation kernels: sort + segment-reduce group-by.

The TPU-native replacement for Presto's hash aggregation stack (reference
presto-main/.../operator/HashAggregationOperator.java:48,
MultiChannelGroupByHash.java, aggregation/builder/
InMemoryHashAggregationBuilder.java): instead of an open-addressing hash
table over channels, we sort rows by their group keys (lexicographic
``lax.sort``), detect segment boundaries, assign dense group ids by prefix
sum, and run ``jax.ops.segment_*`` reductions — everything static-shape and
branch-free on the VPU. NULL is a group key value like any other (SQL GROUP
BY semantics), encoded as a leading null-rank sort operand.

Two-phase execution mirrors Presto's PARTIAL/FINAL split (reference
AggregationNode.Step): partial emits state columns (sum+count, min+count...),
final re-aggregates states after an exchange. States are ordinary columns, so
the exchange layer needs no special serialization.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .prefix import prefix_sum
# imported EAGERLY so its module-level device constants (SIGN64, MASK32)
# are created outside any jit trace: a first import inside a traced
# kernel leaks tracer-scoped constants and fails the compile
# (UnexpectedTracerError seen on decimal aggregations whose first use
# was inside grouped_aggregate's jit)
from . import int128 as _int128  # noqa: F401
from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity
from ..types import Type

_VARIANCE_FNS = ("var_samp", "var_pop", "stddev_samp",
                 "stddev_pop")
_SUPPORTED = ("sum", "count", "count_star", "min", "max", "avg",
              "var_samp", "var_pop", "stddev_samp", "stddev_pop",
              "bool_and", "bool_or", "approx_percentile",
              "approx_distinct")
#: aggregates whose GROUPED form drains the input into one exact
#: 'single'-mode pass (reference computes these with QuantileDigest
#: sketches — state/DigestAndPercentileState.java). The GLOBAL numeric
#: form instead carries bounded mergeable histogram state through
#: partial -> exchange -> final like every other aggregate
#: (ops/sketch.py qd_*); only grouped and string-input forms drain,
#: because a dense per-group tile would be O(groups x bins) and
#: dictionary ranks are batch-local (not mergeable across shards).
DRAIN_FNS = ("approx_percentile",)


def has_drain_agg(aggs) -> bool:
    return any(a.fn in DRAIN_FNS for a in aggs)


def percentile_drains(aggs, input_types, grouped: bool) -> bool:
    """True when approx_percentile aggregates must run as an exact
    drain (see DRAIN_FNS): grouped aggregations and string inputs.
    ``input_types`` is the child schema's type list."""
    drains = [a for a in aggs if a.fn in DRAIN_FNS]
    if not drains:
        return False
    if grouped:
        return True
    # accepts AggSpec (.input) and planner PlanAgg (.arg) alike
    return any(
        input_types[a.input if hasattr(a, "input") else a.arg].is_string
        for a in drains)


#: largest fused key-domain the broadcast-compare dense reducers handle
#: ([rows, K] masked reduce); past this the scatter reducers take over
_DENSE_GROUP_LIMIT = 4096

#: largest fused key-domain of the stats-bounded dense SCATTER group-by
#: (one i32 scatter per digit over K slots — ~85-110M updates/s on v5e vs
#: ~8M/s for the 64-bit path and an 82s compile for the 3-operand
#: lax.sort it replaces); past this the mostly-empty slot table stops
#: paying for itself and the sort-segment path wins. Shared with the
#: planner's rewrite gate (optimizer._attach_group_bounds).
DENSE_SCATTER_LIMIT = 1 << 21


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: fn over an input column (None for count(*))."""

    fn: str
    input: Optional[int]          # column index in the input batch
    output_type: Type
    name: str = ""                # output column name
    # mask channel: rows where this boolean column is false don't feed
    # this aggregate (reference AggregationNode.Aggregation mask — the
    # MarkDistinct lowering of DISTINCT aggregates)
    mask: Optional[int] = None
    # static scalar parameter (approx_percentile's p)
    param: Optional[float] = None

    def __post_init__(self):
        assert self.fn in _SUPPORTED, self.fn

    # state layout produced by partial mode / consumed by final mode
    def state_types(self) -> List[Tuple[str, Type]]:
        base = self.name or self.fn
        if self.fn == "approx_percentile":
            # fixed-size log-linear histogram: the bounded mergeable
            # state the reference ships between partial and final steps
            # (state/DigestAndPercentileState.java); only the GLOBAL
            # numeric form uses it (grouped/string forms drain — see
            # DRAIN_FNS)
            from .sketch import QD_BINS
            return [(f"{base}$qdig", T.QdigestStateType(QD_BINS))]
        if self.fn == "approx_distinct":
            # fixed-size HLL register vector: the bounded mergeable state
            # the reference ships between partial and final steps
            # (state/HyperLogLogState.java); param carries the max
            # standard error
            from .sketch import hll_m
            return [(f"{base}$hll", T.HllStateType(hll_m(self.param)))]
        if self.fn in ("count", "count_star"):
            return [(f"{base}$cnt", T.BIGINT)]
        if self.fn == "avg":
            return [(f"{base}$sum", self._sum_type()), (f"{base}$cnt", T.BIGINT)]
        if self.fn in _VARIANCE_FNS:
            # central moments (mean, m2, count), not sum/sum-of-squares:
            # sumsq - sum^2/n cancels catastrophically for large-mean
            # low-variance data (reference
            # aggregation/state/CentralMomentsState.java stores central
            # moments for the same reason)
            return [(f"{base}$mean", T.DOUBLE), (f"{base}$m2", T.DOUBLE),
                    (f"{base}$cnt", T.BIGINT)]
        if self.fn in ("bool_and", "bool_or"):
            return [(f"{base}$val", T.INTEGER), (f"{base}$cnt", T.BIGINT)]
        return [(f"{base}$val", self._sum_type() if self.fn == "sum" else self.output_type),
                (f"{base}$cnt", T.BIGINT)]

    def _sum_type(self) -> Type:
        if isinstance(self.output_type, T.DecimalType):
            # decimal sums/avgs accumulate in decimal(38, s) two-limb
            # state like the reference (DecimalSumAggregation Int128
            # state; ops/int128.py digit-plane exact sums)
            return T.DecimalType(38, self.output_type.scale)
        return self.output_type


def mark_distinct_flags(batch: Batch,
                        cols: Sequence[int]) -> jnp.ndarray:
    """True at the first live occurrence of each distinct tuple of
    ``cols`` (reference operator/MarkDistinctOperator.java +
    MarkDistinctHash — hash-set membership replaced by sort + boundary +
    scatter-back, the branch-free device shape). Dead rows are False."""
    ops: List[jnp.ndarray] = [
        jnp.where(batch.row_mask, 0, 1).astype(jnp.int32)]
    for ci in cols:
        c = batch.columns[ci]
        data = c.data
        ops.append(jnp.where(c.validity, 0, 1).astype(jnp.int32))
        if getattr(data, "ndim", 1) == 2:
            from . import int128 as I
            ops.append(jnp.where(c.validity, I.hi(data),
                                 jnp.zeros_like(I.hi(data))))
            ops.append(jnp.where(c.validity, I.lo(data),
                                 jnp.zeros_like(I.lo(data))))
            continue
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        ops.append(jnp.where(c.validity, data, jnp.zeros_like(data)))
    idx = jnp.arange(batch.capacity, dtype=jnp.int64)
    out = jax.lax.sort(ops + [idx], num_keys=len(ops), is_stable=True)
    s_live = out[0] == 0
    s_idx = out[-1]
    diff = jnp.zeros_like(s_live)
    for op in out[1:len(ops)]:
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.zeros_like(s_live).at[0].set(True)
    boundary = s_live & (diff | first)
    return jnp.zeros(batch.capacity, dtype=bool).at[s_idx].set(boundary)


def _group_key_ops(batch: Batch,
                   group_indices: Sequence[int]) -> List[jnp.ndarray]:
    """Lexicographic sort operands for GROUP BY keys: [dead_rank, then per
    key (null_rank, null-neutralized data)]. Shared by every kernel whose
    output rows must align positionally across separate sorts of the same
    batch (grouped_aggregate and the percentile drain)."""
    dead_rank = jnp.where(batch.row_mask, 0, 1).astype(jnp.int32)
    key_ops: List[jnp.ndarray] = [dead_rank]
    for gi in group_indices:
        c = batch.columns[gi]
        data = c.data
        key_ops.append(jnp.where(c.validity, 0, 1).astype(jnp.int32))  # nulls last
        if getattr(data, "ndim", 1) == 2:
            # long-decimal limb pairs: lexicographic (hi, unsigned lo)
            # is value order (ops/int128.py sortable_lo)
            from . import int128 as I
            key_ops.append(jnp.where(c.validity, I.hi(data),
                                     jnp.zeros_like(I.hi(data))))
            key_ops.append(jnp.where(c.validity, I.sortable_lo(data),
                                     jnp.zeros_like(I.lo(data))))
            continue
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        # neutralize NULL rows' data so stale values can't split NULL groups
        key_ops.append(jnp.where(c.validity, data, jnp.zeros_like(data)))
    return key_ops


def _boundary_groups(s_keys, s_mask):
    """Boundary/group-id/start-index machinery over sorted key operands."""
    diff = jnp.zeros_like(s_mask)
    for op in s_keys:
        diff = diff | (op != jnp.roll(op, 1))
    first = jnp.zeros_like(s_mask).at[0].set(True)
    boundary = s_mask & (diff | first)
    group_id = jnp.maximum(prefix_sum(boundary.astype(jnp.int64)) - 1, 0)
    num_groups = jnp.sum(boundary.astype(jnp.int64))
    return boundary, group_id, num_groups


def _group_sort(batch: Batch, group_indices: Sequence[int]):
    """Sort rows by group keys; return (key_operands, permuted batch arrays).

    Returns (sorted_cols, sorted_validity, sorted_mask, boundary, group_id,
    num_groups) where boundary marks the first live row of each group.

    Only the key operands plus a row index enter ``lax.sort``; payload
    columns are gathered by the resulting permutation. TPU variadic-sort
    compile time grows superlinearly with operand count (measured on v5e:
    ~215s cold for a 10-operand sort vs ~20s for keys+iota), so carrying
    the whole batch through the comparator is never worth it.
    """
    key_ops = _group_key_ops(batch, group_indices)
    idx = jnp.arange(batch.capacity, dtype=jnp.int32)
    out = jax.lax.sort(key_ops + [idx], num_keys=len(key_ops),
                       is_stable=True)
    s_keys = out[1:-1]                    # sorted key operands (minus dead rank)
    perm = out[-1]
    s_mask = jnp.take(batch.row_mask, perm, axis=0)
    s_data = [jax.tree_util.tree_map(
        lambda a: jnp.take(a, perm, axis=0), c.data) for c in batch.columns]
    s_valid = [jnp.take(c.validity, perm, axis=0) for c in batch.columns]
    boundary, group_id, num_groups = _boundary_groups(s_keys, s_mask)
    return s_data, s_valid, s_mask, boundary, group_id, num_groups


def _wide_state_aggs(aggs: Sequence["AggSpec"]) -> bool:
    """Aggregates whose states need the sort path's leading row dim
    (HLL register tiles, decimal(38) limb pairs)."""
    return any(a.fn == "approx_distinct" for a in aggs) or any(
        getattr(st, "storage_width", None)
        for a in aggs if a.fn not in DRAIN_FNS
        for _, st in a.state_types())


def dense_path_selected(batch: "Batch", group_indices: Sequence[int],
                        aggs: Sequence["AggSpec"],
                        output_capacity: Optional[int] = None,
                        key_bounds=None) -> bool:
    """Host-only mirror of grouped_aggregate's kernel dispatch: True when
    this batch/grouping takes the dense composite-code path (broadcast or
    scatter), False when it sorts. The executor reports it (obs metric +
    EXPLAIN ANALYZE) without tracing anything."""
    if has_drain_agg(aggs) or _wide_state_aggs(aggs):
        return False
    cap = output_capacity or batch.capacity
    return dense_group_plan(batch, group_indices, cap,
                            key_bounds) is not None


@dataclasses.dataclass(frozen=True)
class DenseGroupPlan:
    """Host-static plan for the composite dense group code: one
    mixed-radix component per key (component 0 = NULL). ``los[i]`` is the
    integer key's stats-derived lower bound (None for dictionary/boolean
    keys, whose domain comes from the data itself); ``scatter`` selects
    the segment-scatter reducers over the [rows, K] broadcast reduce."""

    sizes: Tuple[int, ...]
    los: Tuple[Optional[int], ...]
    K: int
    scatter: bool


def dense_group_plan(batch: Batch, group_indices: Sequence[int],
                     cap: int,
                     key_bounds: Optional[Sequence[
                         Optional[Tuple[int, int]]]] = None
                     ) -> Optional[DenseGroupPlan]:
    """Dense-path dispatch rule (host-only — reads column aux data, no
    device math, so the executor can also call it to report which kernel
    a grouping takes). A key contributes a component when its domain is
    host-known: dictionary-coded strings (|vocab|), booleans, or integer
    keys with stats-derived [lo, hi] bounds from the planner
    (AggregationNode.key_bounds — the reference BigintGroupByHash
    dense-array mode generalized to mixed-radix composite keys). Returns
    None when any domain is unknown or the product overflows the limit —
    the sort-segment path then runs unchanged."""
    sizes: List[int] = []
    los: List[Optional[int]] = []
    bounded = False
    for j, gi in enumerate(group_indices):
        c = batch.columns[gi]
        kb = key_bounds[j] if key_bounds else None
        if c.type.is_string and c.dictionary is not None:
            sizes.append(len(c.dictionary) + 1)
            los.append(None)
        elif c.data.dtype == jnp.bool_:
            sizes.append(3)
            los.append(None)
        elif (kb is not None and getattr(c.data, "ndim", 1) == 1
                and jnp.issubdtype(c.data.dtype, jnp.integer)):
            lo, hi = int(kb[0]), int(kb[1])
            if hi < lo:
                return None
            sizes.append(hi - lo + 2)
            los.append(lo)
            bounded = True
        else:
            return None
    K = 1
    for s in sizes:
        K *= s
    limit = min(cap, DENSE_SCATTER_LIMIT if bounded else _DENSE_GROUP_LIMIT)
    if not 0 < K <= limit:
        return None
    return DenseGroupPlan(tuple(sizes), tuple(los), K,
                          scatter=bounded or K > _DENSE_GROUP_LIMIT)


def _dense_group_code(batch: Batch, group_indices: Sequence[int],
                      plan: DenseGroupPlan) -> jnp.ndarray:
    """Fused dense group slot: slot = mixed-radix(key components),
    component 0 = NULL. Group ids come straight from the data, so
    aggregation is a single segment-reduce pass with trivial compile
    time — no comparator, no permutation. A live key outside its stats
    bound CLAMPS into the domain (the slot table must stay in-bounds);
    the executor independently raises STATS_BOUND_VIOLATION for such
    rows through the row-error channel, so a misgrouped result never
    escapes the query."""
    code = jnp.zeros(batch.capacity, dtype=jnp.int32)
    for gi, size, lo in zip(group_indices, plan.sizes, plan.los):
        c = batch.columns[gi]
        if lo is None:
            comp = jnp.where(c.validity, c.data.astype(jnp.int32) + 1, 0)
        else:
            shifted = jnp.clip(c.data.astype(jnp.int64) - lo + 1, 1,
                               size - 1).astype(jnp.int32)
            comp = jnp.where(c.validity, shifted, 0)
        code = code * size + comp
    return code


def _dense_key_columns(batch: Batch, group_indices: Sequence[int],
                       plan: DenseGroupPlan, cap: int,
                       out_mask: jnp.ndarray) -> List[Column]:
    """Decode slot indices 0..K-1 back into key columns (static mixed-radix
    decode — becomes constants under jit), padded to ``cap``."""
    K = plan.K
    slots = np.arange(K, dtype=np.int64)
    comps: List[np.ndarray] = []
    for size in reversed(list(plan.sizes)):
        comps.append(slots % size)
        slots = slots // size
    comps.reverse()
    key_cols = []
    for gi, comp, lo in zip(group_indices, comps, plan.los):
        c = batch.columns[gi]
        valid = jnp.pad(jnp.asarray(comp > 0), (0, cap - K)) & out_mask
        if lo is not None:
            data = jnp.pad(jnp.asarray(
                lo + np.maximum(comp - 1, 0)).astype(c.data.dtype),
                (0, cap - K))
        elif c.data.dtype == jnp.bool_:
            data = jnp.pad(jnp.asarray(comp == 2), (0, cap - K))
        else:
            data = jnp.pad(
                jnp.asarray(np.maximum(comp - 1, 0)).astype(c.data.dtype),
                (0, cap - K))
        key_cols.append(Column(c.type, data, valid, c.dictionary))
    return key_cols


class _SegReducers:
    """Group reductions over a precomputed group id via ``segment_*``
    scatter ops — the right shape when group ids are dense from a sort
    (num_segments is large, ids are sorted runs).

    When ``starts`` is provided (sorted-run group ids with per-group
    start indices, absent groups pointing one past the end), 64-bit
    sums take the scan path instead of the scatter: i64 goes through
    the Pallas digit-plane cumsum (ops/pallas_scan.py, exact), f64
    through an XLA cumsum + boundary differences — the 64-bit scatter
    runs ~8M rows/s on this chip while linear scans stream 50-80x
    faster. f64 prefix differences round differently than per-group
    scatter order, which SQL sum(double) permits."""

    def __init__(self, group_id: jnp.ndarray, cap: int,
                 starts: Optional[jnp.ndarray] = None,
                 n_rows: Optional[int] = None):
        self.gid, self.cap = group_id, cap
        self.starts, self.n_rows = starts, n_rows

    def count(self, valid):
        return self.sum(valid.astype(jnp.int64))

    def sum(self, x):
        if self.starts is not None and getattr(x, "ndim", 0) == 1:
            from .pallas_scan import pallas_supported, segment_sum_sorted_i64
            if x.dtype == jnp.int64 and pallas_supported():
                return segment_sum_sorted_i64(
                    x, self.starts, self.cap,
                    max_rows_per_group=self.n_rows)
            if x.dtype == jnp.float64 and pallas_supported():
                n = x.shape[0]
                csum = prefix_sum(x)
                prev = jnp.clip(self.starts - 1, 0, n - 1)
                ends = jnp.concatenate(
                    [jnp.clip(self.starts[1:] - 1, 0, n - 1),
                     jnp.full((1,), n - 1, self.starts.dtype)])
                hi = jnp.take(csum, ends, axis=0)
                lo = jnp.where(self.starts <= 0, 0.0,
                               jnp.take(csum, prev, axis=0))
                return hi - lo
        return jax.ops.segment_sum(x, self.gid, num_segments=self.cap)

    def min(self, x):
        return jax.ops.segment_min(x, self.gid, num_segments=self.cap)

    def max(self, x):
        return jax.ops.segment_max(x, self.gid, num_segments=self.cap)

    def hll(self, valid, hashed, m):
        """HLL register update: one segment_max over flattened
        (group, bucket) slots (ops/sketch.py)."""
        from .sketch import hll_update
        return hll_update(self.gid, valid, hashed, self.cap, m)

    def gather(self, per_group):
        return per_group[self.gid]


class _DenseReducers:
    """Group reductions for a small static slot count K via a broadcast
    compare + axis-0 reduce (no scatter: a TPU scatter-add over 8M rows
    costs ~0.5s while the [N, K] masked reduce is memory-bound — measured
    ~8x faster end-to-end on v5e)."""

    def __init__(self, code: jnp.ndarray, K: int):
        self.code, self.cap = code, K
        self._match = None

    def _m(self):
        if self._match is None:
            self._match = (self.code[:, None]
                           == jnp.arange(self.cap,
                                         dtype=self.code.dtype)[None, :])
        return self._match

    def count(self, valid):
        # accumulate in i32 (counts < 2^31 within one batch): this
        # broadcast reduce is memory-bound and i64 doubles its traffic
        return self.sum(valid.astype(jnp.int32)).astype(jnp.int64)

    def sum(self, x):
        return jnp.sum(jnp.where(self._m(), x[:, None],
                                 jnp.zeros((), x.dtype)), axis=0)

    def min(self, x):
        return jnp.min(jnp.where(self._m(), x[:, None],
                                 _max_sentinel(x.dtype)), axis=0)

    def max(self, x):
        return jnp.max(jnp.where(self._m(), x[:, None],
                                 _min_sentinel(x.dtype)), axis=0)

    def gather(self, per_group):
        return per_group[self.code]


class _ScatterReducers:
    """Group reductions over a dense i32 composite key code via
    ``segment_*`` scatters — the bounded-domain no-sort path for key
    spaces too wide for the [rows, K] broadcast reduce above. The group
    id needs no sort and no boundary pass (it IS the key), so the whole
    aggregation is a handful of scatters: counts are one i32 scatter,
    exact 64-bit sums go through the i32 digit scatters of
    ops/scatter_agg.py (the f64/i64 scatter is the ~14x cliff on this
    chip), and f64 sums scatter directly in f64 (SQL sum(double)
    tolerates the reduction order; the magnitude is still exact f64
    adds). Signed inputs scatter positive and negative magnitudes
    separately — the digit split needs non-negative values."""

    def __init__(self, code: jnp.ndarray, cap: int, n_rows: int):
        self.gid, self.cap, self.n_rows = code, cap, n_rows

    def count(self, valid):
        ones = jnp.where(valid, jnp.int32(1), jnp.int32(0))
        c = jax.ops.segment_sum(ones, self.gid, num_segments=self.cap)
        return c.astype(jnp.int64)

    def sum(self, x):
        if x.dtype == jnp.int64 and getattr(x, "ndim", 1) == 1:
            from .scatter_agg import segment_sum_exact
            pos = segment_sum_exact(jnp.maximum(x, 0), self.gid,
                                    self.cap, self.n_rows, value_bits=62)
            neg = segment_sum_exact(jnp.maximum(-x, 0), self.gid,
                                    self.cap, self.n_rows, value_bits=62)
            return pos - neg
        return jax.ops.segment_sum(x, self.gid, num_segments=self.cap)

    def min(self, x):
        return jax.ops.segment_min(x, self.gid, num_segments=self.cap)

    def max(self, x):
        return jax.ops.segment_max(x, self.gid, num_segments=self.cap)

    def hll(self, valid, hashed, m):
        from .sketch import hll_update
        return hll_update(self.gid, valid, hashed, self.cap, m)

    def gather(self, per_group):
        return per_group[self.gid]


def _segment_aggs(
    aggs: Sequence[AggSpec],
    col_data: Sequence[jnp.ndarray],
    col_valid: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    red,
    from_states: bool,
    col_dicts: Optional[Sequence[Optional[Tuple[str, ...]]]] = None,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Per-aggregate (value_arrays...) segment reductions.

    Returns, per agg, a list of (data, counts-ish) arrays matching its state
    layout when ``from_states`` is False, or merged states when True.
    """
    results = []
    state_cursor = 0
    for agg in aggs:
        if from_states:
            # inputs are state columns in layout order
            n_state = len(agg.state_types())
            s_cols = list(range(state_cursor, state_cursor + n_state))
            state_cursor += n_state
            if agg.fn == "approx_percentile":
                raise NotImplementedError(
                    "grouped approx_percentile is drain-only "
                    "(see percentile_drains)")
            if agg.fn == "approx_distinct":
                # HLL merge = per-bucket max of register rows [n, m];
                # 0 is the register identity so dead rows drop out
                regs_in = col_data[s_cols[0]]
                live2 = mask[:, None]
                merged = red.max(jnp.where(live2, regs_in,
                                           jnp.zeros_like(regs_in)))
                results.append((jnp.maximum(merged, 0),))
                continue
            if agg.fn in ("count", "count_star"):
                cnt_in = jnp.where(mask, col_data[s_cols[0]], 0)
                cnt = red.sum(cnt_in)
                results.append((cnt,))
                continue
            if agg.fn in _VARIANCE_FNS:
                # merge partial (mean, m2, n) states: Chan's parallel
                # combination generalized to k partials —
                # M2 = sum(m2_i + n_i * (mean_i - mean)^2)
                m_in = col_data[s_cols[0]]
                m2_in = col_data[s_cols[1]]
                cnt_raw = col_data[s_cols[2]]
                live = mask & (cnt_raw > 0)
                nw = jnp.where(live, cnt_raw, 0)
                cnt = red.sum(nw)
                nf = nw.astype(jnp.float64)
                n = jnp.maximum(cnt, 1).astype(jnp.float64)
                wsum = red.sum(nf * jnp.where(live, m_in, 0.0))
                mean = wsum / n
                dev = m_in - red.gather(mean)
                # corrected combine: (sum n_i*dev_i)^2/n cancels the
                # weighted-sum rounding error in the computed mean
                wdev = red.sum(jnp.where(live, nf * dev, 0.0))
                m2 = red.sum(jnp.where(live, m2_in + nf * dev * dev, 0.0)) - wdev * wdev / n
                results.append((mean + wdev / n, m2, cnt))
                continue
            stype = agg.state_types()[0][1]
            if isinstance(stype, T.DecimalType) and stype.is_long:
                from . import int128 as I
                val_in = col_data[s_cols[0]]        # [n, 2] limbs
                cnt_raw = col_data[s_cols[1]]
                cnt = red.sum(jnp.where(mask, cnt_raw, 0))
                live = mask & (cnt_raw > 0)
                if agg.fn in ("sum", "avg"):
                    val = _checked_sum128(val_in, live, red.sum)
                else:
                    val = _minmax128(val_in, live, red, agg.fn)
                results.append((val, cnt))
                continue
            val_in = col_data[s_cols[0]]
            cnt_raw = col_data[s_cols[1]]
            cnt_in = jnp.where(mask, cnt_raw, 0)
            cnt = red.sum(cnt_in)
            live = mask & (cnt_raw > 0)
            vocab = col_dicts[s_cols[0]] if col_dicts else None
            if vocab is not None and agg.fn in ("min", "max"):
                val = _rank_reduce(val_in, live, red, vocab, agg.fn)
            elif agg.fn in ("sum", "avg"):
                contrib = jnp.where(live, val_in, jnp.zeros_like(val_in))
                val = red.sum(contrib)
            elif agg.fn in ("bool_and", "min"):
                sent = _max_sentinel(val_in.dtype)
                contrib = jnp.where(live, val_in, sent)
                val = red.min(contrib)
            else:  # max / bool_or
                sent = _min_sentinel(val_in.dtype)
                contrib = jnp.where(live, val_in, sent)
                val = red.max(contrib)
            results.append((val, cnt))
            continue
        # raw-input mode
        if agg.fn == "count_star":
            cnt = red.count(mask)
            results.append((cnt,))
            continue
        data = col_data[agg.input]
        valid = col_valid[agg.input] & mask
        if agg.mask is not None:
            valid = valid & col_data[agg.mask].astype(bool)
        if agg.fn == "approx_distinct":
            from .sketch import hashed_column, hll_m
            vocab = col_dicts[agg.input] if col_dicts else None
            hashed = hashed_column(data, vocab)
            results.append((red.hll(valid, hashed, hll_m(agg.param)),))
            continue
        cnt = red.count(valid)
        if agg.fn == "count":
            results.append((cnt,))
            continue
        if agg.fn in _VARIANCE_FNS:
            # corrected two-pass central moments: mean first, then squared
            # deviations with the (sum dev)^2/n correction term that
            # cancels the first-pass sum's rounding error — stable for
            # any magnitude
            x = data.astype(jnp.float64)
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            s = red.sum(jnp.where(valid, x, 0.0))
            mean = s / n
            dev = jnp.where(valid, x - red.gather(mean), 0.0)
            s1 = red.sum(dev)
            m2 = red.sum(dev * dev) - s1 * s1 / n
            results.append((mean + s1 / n, m2, cnt))
            continue
        if agg.fn in ("bool_and", "bool_or"):
            x = data.astype(jnp.int32)
            if agg.fn == "bool_and":
                contrib = jnp.where(valid, x, jnp.int32(1))
                val = red.min(contrib)
            else:
                contrib = jnp.where(valid, x, jnp.int32(0))
                val = red.max(contrib)
            results.append((val, cnt))
            continue
        vocab = col_dicts[agg.input] if col_dicts else None
        if vocab is not None and agg.fn in ("min", "max"):
            val = _rank_reduce(data, valid, red, vocab, agg.fn)
            results.append((val, cnt))
            continue
        acc_t = agg.state_types()[0][1]
        if isinstance(acc_t, T.DecimalType) and acc_t.is_long:
            # decimal(38) accumulation: short inputs sign-extend to
            # limbs, long inputs pass through; sums are exact digit-
            # plane scatters (ops/int128.py)
            from . import int128 as I
            x = data if data.ndim == 2 else I.from_i64(data)
            if agg.fn in ("sum", "avg"):
                val = _checked_sum128(x, valid, red.sum)
            else:
                val = _minmax128(x, valid, red, agg.fn)
            results.append((val, cnt))
            continue
        acc_dtype = acc_t.storage_dtype
        x = data.astype(acc_dtype)
        if agg.fn in ("sum", "avg"):
            if isinstance(acc_t, T.DecimalType) and isinstance(agg.output_type, T.DecimalType):
                pass  # same scale accumulate
            contrib = jnp.where(valid, x, jnp.zeros_like(x))
            val = red.sum(contrib)
        elif agg.fn == "min":
            contrib = jnp.where(valid, x, _max_sentinel(acc_dtype))
            val = red.min(contrib)
        else:
            contrib = jnp.where(valid, x, _min_sentinel(acc_dtype))
            val = red.max(contrib)
        results.append((val, cnt))
    return results


def _checked_sum128(x: jnp.ndarray, live: jnp.ndarray, red_sum) -> jnp.ndarray:
    """Exact 128-bit sum of limb tiles [n, 2] with overflow poisoning:
    groups whose true sum exceeds 38 digits (or that merge an already
    poisoned partial) yield the OVERFLOW_SENTINEL, which raises
    NUMERIC_VALUE_OUT_OF_RANGE when the value is decoded (the deferred
    analogue of the reference DecimalSumAggregation throw)."""
    from . import int128 as I
    planes = jnp.where(live[:, None], I.digit_sum_tiles(x), 0)
    val, ovf = I.from_digit_sum_tiles_checked(red_sum(planes))
    ovf = ovf | ~I.fits_decimal(val, 38)
    poisoned = red_sum((live & I.is_overflow_sentinel(x))
                       .astype(jnp.int32)) > 0
    sent = jnp.broadcast_to(jnp.asarray(I.OVERFLOW_SENTINEL), val.shape)
    return jnp.where((ovf | poisoned)[..., None], sent, val)


class _GlobalReducer:
    """Single-group reducer with the _SegReducers surface (min/max/sum
    collapse all rows; gather broadcasts), so grouped and global code
    paths share the int128 kernels below."""

    def sum(self, x):
        return jnp.sum(x, axis=0)

    def min(self, x):
        return jnp.min(x, axis=0)

    def max(self, x):
        return jnp.max(x, axis=0)

    def gather(self, per_group):
        return per_group


def _minmax128(x: jnp.ndarray, live: jnp.ndarray, red, fn: str) -> jnp.ndarray:
    """Grouped min/max over int128 limb tiles [n, 2]: lexicographic
    (hi, unsigned lo) in two segment reductions — reduce hi, then lo
    among rows tied at the winning hi."""
    from . import int128 as I
    h = I.hi(x)
    l = I.sortable_lo(x)
    op = red.min if fn == "min" else red.max
    sent_h = _max_sentinel(h.dtype) if fn == "min" else _min_sentinel(h.dtype)
    mh = op(jnp.where(live, h, sent_h))
    tie = live & (h == red.gather(mh))
    ml = op(jnp.where(tie, l, sent_h))
    return I.pack(mh, ml ^ I.SIGN64)


def _finalize_dec128(agg: AggSpec, val: jnp.ndarray, cnt: jnp.ndarray):
    """Shared long-decimal finalize: avg divide (poisoned past the
    2^31-row divisor bound and through overflowed sums), short-output
    narrowing. ``val`` is [..., 2] limbs."""
    from . import int128 as I
    out_t = agg.output_type
    short_out = isinstance(out_t, T.DecimalType) and not out_t.is_long
    if agg.fn == "avg":
        den = jnp.clip(cnt, 1, 1 << 31)
        q = I.div_round_half_up(val, den)
        # poisoned sums stay poisoned; counts past the short-division
        # bound poison too rather than divide by a clipped count
        bad = I.is_overflow_sentinel(val) | (cnt > (1 << 31))
        q = I.where(bad, jnp.broadcast_to(jnp.asarray(I.OVERFLOW_SENTINEL),
                                          q.shape), q)
        return (I.lo(q) if short_out else q)
    return (I.lo(val) if short_out else val)


def _rank_reduce(codes: jnp.ndarray, live: jnp.ndarray, red,
                 vocab: Tuple[str, ...], fn: str) -> jnp.ndarray:
    """min/max over dictionary codes in LEXICOGRAPHIC order: map codes to
    ranks, segment-reduce, map the winning rank back to a code (reference
    MinMaxHelpers over VARCHAR; codes are appearance-ordered, not
    sorted)."""
    from .sort import rank_codes, unrank_table
    ranks = rank_codes(codes, vocab).astype(jnp.int64)
    if fn == "min":
        r = red.min(jnp.where(live, ranks, jnp.iinfo(jnp.int64).max))
    else:
        r = red.max(jnp.where(live, ranks, -1))
    table = unrank_table(vocab)
    safe = jnp.clip(r, 0, table.shape[0] - 1)
    return jnp.take(table, safe, axis=0)


def _rank_reduce_scalar(codes: jnp.ndarray, live: jnp.ndarray,
                        vocab: Tuple[str, ...], fn: str) -> jnp.ndarray:
    """Global (single-group) variant of _rank_reduce."""
    from .sort import rank_codes, unrank_table
    ranks = rank_codes(codes, vocab).astype(jnp.int64)
    if fn == "min":
        r = jnp.min(jnp.where(live, ranks, jnp.iinfo(jnp.int64).max))
    else:
        r = jnp.max(jnp.where(live, ranks, -1))
    table = unrank_table(vocab)
    return jnp.take(table, jnp.clip(r, 0, table.shape[0] - 1))


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype=dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype=dtype)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype=dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype=dtype)


def _variance_out(agg, mean, m2, cnt):
    """(mean, m2, count) central-moment state -> variance/stddev."""
    del mean
    n = jnp.maximum(cnt, 1).astype(jnp.float64)
    pop = agg.fn in ("var_pop", "stddev_pop")
    den = n if pop else jnp.maximum(n - 1.0, 1.0)
    var = jnp.maximum(m2, 0.0) / den
    out = jnp.sqrt(var) if agg.fn.startswith("stddev") else var
    valid = (cnt > 0) if pop else (cnt > 1)
    return out, valid


def _finalize(agg: AggSpec, parts: Tuple[jnp.ndarray, ...]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """state -> (output data, output validity)."""
    if agg.fn in ("count", "count_star"):
        return parts[0], jnp.ones_like(parts[0], dtype=bool)
    if agg.fn == "approx_distinct":
        from .sketch import hll_estimate
        regs = parts[0]
        return hll_estimate(regs), jnp.ones(regs.shape[:-1], dtype=bool)
    if agg.fn in _VARIANCE_FNS:
        return _variance_out(agg, *parts)
    val, cnt = parts
    valid = cnt > 0
    if agg.fn in ("bool_and", "bool_or"):
        return val > 0, valid
    if val.ndim == 2:
        # long-decimal limb state (sum/avg/min/max over decimals)
        return _finalize_dec128(agg, val, cnt), valid
    if agg.fn == "avg":
        if isinstance(agg.output_type, T.DecimalType):
            den = jnp.maximum(cnt, 1)
            q = val / den
            out = (jnp.sign(q) * jnp.floor(jnp.abs(val) / den + 0.5)).astype(jnp.int64)
            return out, valid
        den = jnp.maximum(cnt, 1).astype(val.dtype)
        return val / den, valid
    out = val.astype(agg.output_type.storage_dtype)
    return out, valid


def _percentile_input(batch: Batch, input_idx: int, mask_idx):
    """(valid, sort_value, unrank) for a percentile input column: dictionary
    codes map through lexicographic ranks so value order is string order
    (codes are appearance-ordered); unrank maps the winner back to a code."""
    c = batch.columns[input_idx]
    if getattr(c.data, "ndim", 1) == 2:
        raise NotImplementedError(
            "grouped approx_percentile over decimal(>18) is not "
            "supported (cast to decimal(18,s) or double)")
    valid = c.validity & batch.row_mask
    if mask_idx is not None:
        valid = valid & batch.columns[mask_idx].data.astype(bool)
    vdata = c.data
    unrank = None
    if c.dictionary is not None:
        from .sort import rank_codes, unrank_table
        vdata = rank_codes(vdata, c.dictionary).astype(jnp.int64)
        unrank = unrank_table(c.dictionary)
    elif vdata.dtype == jnp.bool_:
        vdata = vdata.astype(jnp.int32)
    return valid, vdata, unrank


def _select_ks(aggs: Sequence[AggSpec], nvalid: jnp.ndarray):
    """Per-agg nearest-rank index (0-based) within the valid run."""
    ks = []
    for agg in aggs:
        p = float(agg.param if agg.param is not None else 0.5)
        ks.append(jnp.clip(jnp.ceil(p * nvalid).astype(jnp.int64) - 1, 0,
                           jnp.maximum(nvalid - 1, 0)))
    return ks


def _grouped_percentiles(batch: Batch, group_indices: Sequence[int],
                         aggs: Sequence[AggSpec], cap: int):
    """Nearest-rank percentiles per group for aggregates sharing one
    (input, mask): ONE segmented sort by (group keys, value), k selections.
    Valid values sort first within each group, so the k-th smallest valid
    value sits at (group start + k). Group order comes from the shared
    _group_key_ops operands, so outputs align positionally with
    grouped_aggregate's rows."""
    valid, vdata, unrank = _percentile_input(batch, aggs[0].input,
                                             aggs[0].mask)
    key_ops = _group_key_ops(batch, group_indices)
    val_null = jnp.where(valid, 0, 1).astype(jnp.int32)
    vneutral = jnp.where(valid, vdata, jnp.zeros_like(vdata))
    out = jax.lax.sort(key_ops + [val_null, vneutral],
                       num_keys=len(key_ops) + 2, is_stable=False)
    s_live = out[0] == 0
    s_keys = out[1:len(key_ops)]
    s_vnull, s_vals = out[-2], out[-1]
    boundary, group_id, num_groups = _boundary_groups(s_keys, s_live)
    nvalid = jax.ops.segment_sum(
        (s_live & (s_vnull == 0)).astype(jnp.int64), group_id,
        num_segments=cap)
    bidx = jnp.nonzero(boundary, size=cap, fill_value=batch.capacity - 1)[0]
    out_mask = jnp.arange(cap) < num_groups
    results = []
    for k in _select_ks(aggs, nvalid):
        sel = jnp.clip(bidx + k, 0, batch.capacity - 1)
        data = jnp.take(s_vals, sel, axis=0)
        if unrank is not None:
            data = jnp.take(unrank, jnp.clip(data, 0, unrank.shape[0] - 1),
                            axis=0)
        results.append((data, (nvalid > 0) & out_mask))
    return results


def _global_percentiles(batch: Batch, aggs: Sequence[AggSpec]):
    """Single-group nearest-rank percentiles (one sort, k selections)."""
    valid, vdata, unrank = _percentile_input(batch, aggs[0].input,
                                             aggs[0].mask)
    val_null = jnp.where(valid, 0, 1).astype(jnp.int32)
    vneutral = jnp.where(valid, vdata, jnp.zeros_like(vdata))
    _, s_vals = jax.lax.sort([val_null, vneutral], num_keys=2,
                             is_stable=False)
    n = jnp.sum(valid.astype(jnp.int64))
    results = []
    for k in _select_ks(aggs, n):
        data = jnp.take(s_vals, k)
        if unrank is not None:
            data = jnp.take(unrank, jnp.clip(data, 0, unrank.shape[0] - 1))
        results.append((data, n > 0))
    return results


def _drain_groups(aggs):
    """Drain aggs grouped by shared (input, mask) -> one sort per group."""
    groups: dict = {}
    for agg in aggs:
        if agg.fn in DRAIN_FNS:
            groups.setdefault((agg.input, agg.mask), []).append(agg)
    return groups


def _with_drain_aggs(batch: Batch, group_indices, aggs, mode,
                     output_capacity) -> Batch:
    """grouped_aggregate with approx_percentile columns spliced in."""
    if mode != "single":
        raise NotImplementedError(
            "approx_percentile requires single-step aggregation "
            "(the planner routes such plans through a drain)")
    cap = output_capacity or batch.capacity
    regular = [a for a in aggs if a.fn not in DRAIN_FNS]
    # percentile drains align with the regular aggregates POSITIONALLY
    # (both orderings come from the shared _group_key_ops sort), so the
    # dense no-sort path must not reorder groups here
    base = grouped_aggregate(batch, group_indices, regular, "single",
                             output_capacity, allow_dense=False)
    computed = {}
    for shared in _drain_groups(aggs).values():
        for agg, res in zip(shared, _grouped_percentiles(
                batch, group_indices, shared, cap)):
            computed[id(agg)] = res
    nk = len(group_indices)
    out_cols = list(base.columns[:nk])
    out_fields = list(zip(base.schema.names[:nk], base.schema.types[:nk]))
    ri = nk
    for agg in aggs:
        if agg.fn in DRAIN_FNS:
            data, valid = computed[id(agg)]
            out_fields.append((agg.name or agg.fn, agg.output_type))
            out_cols.append(Column(
                agg.output_type,
                data.astype(agg.output_type.storage_dtype), valid,
                batch.columns[agg.input].dictionary
                if agg.output_type.is_string else None))
        else:
            out_cols.append(base.columns[ri])
            out_fields.append((base.schema.names[ri], base.schema.types[ri]))
            ri += 1
    return Batch(Schema(out_fields), out_cols, base.row_mask)


def grouped_aggregate(
    batch: Batch,
    group_indices: Sequence[int],
    aggs: Sequence[AggSpec],
    mode: str = "single",
    output_capacity: Optional[int] = None,
    allow_dense: bool = True,
    key_bounds: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
) -> Batch:
    """GROUP BY aggregation. mode: 'single' | 'partial' | 'final' | 'merge'.

    In 'final' and 'merge' modes the input batch layout must be
    [group key columns..., state columns in agg order...] — i.e. the output
    layout of 'partial' mode (possibly concatenated/exchanged in between).
    'merge' re-combines state rows sharing a key but keeps the state layout
    (Presto's intermediate combine step), enabling hierarchical merging.

    ``key_bounds`` (one Optional[(lo, hi)] per group key, from
    AggregationNode.key_bounds) lets integer keys join the dense
    composite-code path; see dense_group_plan.
    """
    assert mode in ("single", "partial", "final", "merge")
    if has_drain_agg(aggs):
        return _with_drain_aggs(batch, group_indices, aggs, mode,
                                output_capacity)
    cap = output_capacity or batch.capacity
    from_states = mode in ("final", "merge")
    n_keys = len(group_indices)
    if _wide_state_aggs(aggs):
        # wide states (HLL register tiles, decimal(38) limb pairs) need
        # the sort path whose segment ops keep a leading row dim; the
        # dense broadcast-compare reducer would materialize [rows, K, w]
        allow_dense = False
    plan = (dense_group_plan(batch, group_indices, cap, key_bounds)
            if allow_dense else None)
    if plan is not None:
        # no-sort fast path: group id straight from the key data. The
        # output shrinks to the key domain's bucket when the caller left
        # capacity open — a 2^20-row batch grouping into a 10^5-slot
        # domain must not ship 2^20-capacity state columns downstream.
        K = plan.K
        if output_capacity is None:
            cap = min(cap, bucket_capacity(K + 1))
        code = _dense_group_code(batch, group_indices, plan)
        mask = batch.row_mask
        gid = jnp.where(mask, code, K)       # dead rows -> overflow slot
        red = (_ScatterReducers(gid, K + 1, batch.capacity)
               if plan.scatter else _DenseReducers(gid, K + 1))
        occ = red.count(mask)[:K] > 0
        out_mask = jnp.pad(occ, (0, cap - K))
        key_cols = _dense_key_columns(batch, group_indices, plan, cap,
                                      out_mask)
        in_cols = batch.columns[n_keys:] if from_states else batch.columns
        raw = _segment_aggs(
            aggs, [c.data for c in in_cols], [c.validity for c in in_cols],
            mask, red, from_states=from_states,
            col_dicts=[c.dictionary for c in in_cols])
        seg = [tuple(jnp.pad(arr[:K], [(0, cap - K)] + [(0, 0)] * (
            getattr(arr, "ndim", 1) - 1)) for arr in parts)
               for parts in raw]
    else:
        s_data, s_valid, s_mask, boundary, group_id, num_groups = \
            _group_sort(batch, group_indices)

        # group key output: gather the first row of each segment
        bidx = jnp.nonzero(boundary, size=cap,
                           fill_value=batch.capacity - 1)[0]
        out_mask = jnp.arange(cap) < num_groups
        key_cols = []
        for gi in group_indices:
            c = batch.columns[gi]
            key_cols.append(Column(
                c.type,
                jnp.take(s_data[gi], bidx, axis=0),
                jnp.take(s_valid[gi], bidx, axis=0) & out_mask,
                c.dictionary,
            ))

        # sorted-run starts for the scan-path 64-bit sums (absent groups
        # point one past the end — see pallas_scan.segment_sum_sorted_i64)
        starts = jnp.where(out_mask, bidx,
                           batch.capacity).astype(jnp.int32)
        red = _SegReducers(group_id, cap, starts=starts,
                           n_rows=batch.capacity)
        if from_states:
            state_data = s_data[n_keys:]
            state_dicts = [c.dictionary for c in batch.columns[n_keys:]]
            seg = _segment_aggs(aggs, state_data, s_valid[n_keys:], s_mask,
                                red, from_states=True,
                                col_dicts=state_dicts)
        else:
            seg = _segment_aggs(aggs, s_data, s_valid, s_mask,
                                red, from_states=False,
                                col_dicts=[c.dictionary
                                           for c in batch.columns])

    def value_dict(agg: AggSpec):
        """Dictionary for a string-valued min/max output/state column."""
        if agg.fn not in ("min", "max") or agg.input is None:
            return None
        if from_states:
            cursor = 0
            for a in aggs:
                if a is agg:
                    break
                cursor += len(a.state_types())
            return batch.columns[len(group_indices) + cursor].dictionary
        return batch.columns[agg.input].dictionary

    out_cols: List[Column] = list(key_cols)
    out_fields: List[Tuple[str, Type]] = [
        (batch.schema.names[gi], batch.schema.types[gi]) for gi in group_indices
    ]
    if mode in ("partial", "merge"):
        for agg, parts in zip(aggs, seg):
            vd = value_dict(agg)
            for (fname, ftype), arr in zip(agg.state_types(), parts):
                out_fields.append((fname, ftype))
                out_cols.append(Column(
                    ftype, arr.astype(ftype.storage_dtype), out_mask,
                    vd if ftype.is_string else None))
    else:
        for agg, parts in zip(aggs, seg):
            data, valid = _finalize(agg, parts)
            name = agg.name or agg.fn
            out_fields.append((name, agg.output_type))
            out_cols.append(Column(
                agg.output_type, data.astype(agg.output_type.storage_dtype),
                valid & out_mask,
                value_dict(agg) if agg.output_type.is_string else None))
    return Batch(Schema(out_fields), out_cols, out_mask)


def global_aggregate(
    batch: Batch, aggs: Sequence[AggSpec], mode: str = "single"
) -> Batch:
    """Aggregation without GROUP BY: one output row, even over empty input
    (reference AggregationOperator.java global aggregation semantics).
    'merge' consumes state columns and emits merged state columns."""
    assert mode in ("single", "partial", "final", "merge")
    if has_drain_agg(aggs) and mode == "single":
        # exact one-pass path (drain callers and string inputs); the
        # partial/merge/final modes below carry bounded histogram state
        regular = [a for a in aggs if a.fn not in DRAIN_FNS]
        base = global_aggregate(batch, regular, "single")
        computed = {}
        for shared in _drain_groups(aggs).values():
            for agg, res in zip(shared, _global_percentiles(batch, shared)):
                computed[id(agg)] = res
        out_cols2: List[Column] = []
        out_fields2: List[Tuple[str, Type]] = []
        ri = 0
        for agg in aggs:
            if agg.fn in DRAIN_FNS:
                data, valid = computed[id(agg)]
                dt = agg.output_type.storage_dtype
                out_fields2.append((agg.name or agg.fn, agg.output_type))
                out_cols2.append(Column(
                    agg.output_type,
                    jnp.zeros(128, dtype=dt).at[0].set(data.astype(dt)),
                    jnp.zeros(128, dtype=bool).at[0].set(valid),
                    batch.columns[agg.input].dictionary
                    if agg.output_type.is_string else None))
            else:
                out_cols2.append(base.columns[ri])
                out_fields2.append((base.schema.names[ri],
                                    base.schema.types[ri]))
                ri += 1
        return Batch(Schema(out_fields2), out_cols2, base.row_mask)
    cap = 128  # minimum bucket; one live row
    mask = batch.row_mask
    out_fields: List[Tuple[str, Type]] = []
    out_cols: List[Column] = []
    out_mask = jnp.arange(cap) < 1

    def pad(scalar, dtype):
        scalar = jnp.asarray(scalar)
        if scalar.ndim:                    # limb pairs and other vectors
            return jnp.zeros((cap,) + scalar.shape,
                             dtype=dtype).at[0].set(scalar.astype(dtype))
        return jnp.zeros(cap, dtype=dtype).at[0].set(scalar.astype(dtype))

    state_cursor = 0
    for agg in aggs:
        if agg.fn == "approx_percentile":
            from .sketch import QD_BINS, qd_estimate, qd_update
            if mode in ("final", "merge"):
                col = batch.columns[state_cursor]
                state_cursor += 1
                counts = jnp.sum(
                    jnp.where(mask[:, None], col.data,
                              jnp.zeros_like(col.data)), axis=0)
            else:
                c = batch.columns[agg.input]
                if c.dictionary is not None:
                    raise NotImplementedError(
                        "approx_percentile over strings is drain-only "
                        "(see percentile_drains)")
                valid = c.validity & mask
                if agg.mask is not None:
                    valid = valid & \
                        batch.columns[agg.mask].data.astype(bool)
                if getattr(c.data, "ndim", 1) == 2:
                    # long-decimal limbs: histogram over the f64 image
                    # of the unscaled value (monotone, so percentile
                    # bins land identically)
                    from . import int128 as I
                    counts = qd_update(valid, I.to_f64(c.data))
                else:
                    counts = qd_update(valid, c.data.astype(jnp.float64))
            if mode in ("partial", "merge"):
                (fname, ftype) = agg.state_types()[0]
                out_fields.append((fname, ftype))
                out_cols.append(Column(
                    ftype,
                    jnp.zeros((cap, QD_BINS), dtype=jnp.int64).at[0].set(
                        counts),
                    out_mask, None))
            else:
                p = float(agg.param if agg.param is not None else 0.5)
                val, ok = qd_estimate(counts, p)
                dt = agg.output_type.storage_dtype
                if isinstance(agg.output_type, T.DecimalType) \
                        and agg.output_type.is_long:
                    from . import int128 as I
                    val = I.from_f64(jnp.round(val))
                elif not jnp.issubdtype(dt, jnp.floating):
                    val = jnp.round(val)
                out_fields.append((agg.name or agg.fn, agg.output_type))
                out_cols.append(Column(
                    agg.output_type, pad(val, dt),
                    jnp.zeros(cap, dtype=bool).at[0].set(ok), None))
            continue
        if agg.fn == "approx_distinct":
            from .sketch import (hashed_column, hll_estimate, hll_m,
                                 hll_update)
            m = hll_m(agg.param)
            if mode in ("final", "merge"):
                cols = batch.columns[state_cursor:state_cursor + 1]
                state_cursor += 1
                regs = jnp.max(jnp.where(mask[:, None], cols[0].data, 0),
                               axis=0)
            else:
                c = batch.columns[agg.input]
                valid = c.validity & mask
                if agg.mask is not None:
                    valid = valid & \
                        batch.columns[agg.mask].data.astype(bool)
                hashed = hashed_column(c.data, c.dictionary)
                regs = hll_update(jnp.zeros(batch.capacity, jnp.int32),
                                  valid, hashed, 1, m)[0]
            if mode in ("partial", "merge"):
                (fname, ftype) = agg.state_types()[0]
                out_fields.append((fname, ftype))
                out_cols.append(Column(
                    ftype,
                    jnp.zeros((cap, m), dtype=jnp.int32).at[0].set(
                        regs.astype(jnp.int32)),
                    out_mask, None))
            else:
                out_fields.append((agg.name or agg.fn, agg.output_type))
                out_cols.append(Column(
                    agg.output_type, pad(hll_estimate(regs), jnp.int64),
                    jnp.zeros(cap, dtype=bool).at[0].set(True), None))
            continue
        if mode in ("final", "merge"):
            n_state = len(agg.state_types())
            cols = batch.columns[state_cursor:state_cursor + n_state]
            state_cursor += n_state
            if agg.fn in ("count", "count_star"):
                cnt = jnp.sum(jnp.where(mask, cols[0].data, 0))
                parts: Tuple[jnp.ndarray, ...] = (cnt,)
            elif agg.fn in _VARIANCE_FNS:
                # corrected merge of (mean, m2, n) partials — see
                # _segment_aggs
                cnt_raw = cols[2].data
                live = mask & (cnt_raw > 0)
                nf = jnp.where(live, cnt_raw, 0).astype(jnp.float64)
                cnt = jnp.sum(jnp.where(mask, cnt_raw, 0))
                n = jnp.maximum(cnt, 1).astype(jnp.float64)
                mean = jnp.sum(nf * jnp.where(live, cols[0].data, 0.0)) / n
                dev = cols[0].data - mean
                wdev = jnp.sum(jnp.where(live, nf * dev, 0.0))
                m2 = jnp.sum(jnp.where(
                    live, cols[1].data + nf * dev * dev,
                    0.0)) - wdev * wdev / n
                parts = (mean + wdev / n, m2, cnt)
            elif isinstance(agg.state_types()[0][1], T.DecimalType) \
                    and agg.state_types()[0][1].is_long:
                from . import int128 as I
                cnt_raw = cols[1].data
                live = mask & (cnt_raw > 0)
                cnt = jnp.sum(jnp.where(mask, cnt_raw, 0))
                v = cols[0].data               # [n, 2] limb states
                if agg.fn in ("sum", "avg"):
                    val = _checked_sum128(
                        v, live, lambda p: jnp.sum(p, axis=0))
                else:
                    val = _minmax128_scalar(v, live, agg.fn)
                parts = (val, cnt)
            else:
                cnt_raw = cols[1].data
                live = mask & (cnt_raw > 0)
                cnt = jnp.sum(jnp.where(mask, cnt_raw, 0))
                v = cols[0].data
                if (agg.fn in ("min", "max")
                        and cols[0].dictionary is not None):
                    val = _rank_reduce_scalar(v, live, cols[0].dictionary,
                                              agg.fn)
                elif agg.fn in ("sum", "avg", "bool_and", "bool_or"):
                    if agg.fn == "bool_and":
                        val = jnp.min(jnp.where(live, v,
                                                _max_sentinel(v.dtype)))
                    elif agg.fn == "bool_or":
                        val = jnp.max(jnp.where(live, v,
                                                _min_sentinel(v.dtype)))
                    else:
                        val = jnp.sum(jnp.where(live, v,
                                                jnp.zeros_like(v)))
                elif agg.fn == "min":
                    val = jnp.min(jnp.where(live, v, _max_sentinel(v.dtype)))
                else:
                    val = jnp.max(jnp.where(live, v, _min_sentinel(v.dtype)))
                parts = (val, cnt)
        else:
            if agg.fn == "count_star":
                parts = (jnp.sum(mask.astype(jnp.int64)),)
            else:
                c = batch.columns[agg.input]
                valid = c.validity & mask
                if agg.mask is not None:
                    valid = valid & \
                        batch.columns[agg.mask].data.astype(bool)
                cnt = jnp.sum(valid.astype(jnp.int64))
                if agg.fn == "count":
                    parts = (cnt,)
                elif agg.fn in _VARIANCE_FNS:
                    # corrected two-pass central moments (see
                    # _segment_aggs)
                    x = c.data.astype(jnp.float64)
                    n = jnp.maximum(cnt, 1).astype(jnp.float64)
                    mean = jnp.sum(jnp.where(valid, x, 0.0)) / n
                    dev = jnp.where(valid, x - mean, 0.0)
                    s1 = jnp.sum(dev)
                    parts = (mean + s1 / n,
                             jnp.sum(dev * dev) - s1 * s1 / n, cnt)
                elif agg.fn in ("bool_and", "bool_or"):
                    x = c.data.astype(jnp.int32)
                    if agg.fn == "bool_and":
                        val = jnp.min(jnp.where(valid, x, jnp.int32(1)))
                    else:
                        val = jnp.max(jnp.where(valid, x, jnp.int32(0)))
                    parts = (val, cnt)
                elif (agg.fn in ("min", "max")
                      and c.dictionary is not None):
                    val = _rank_reduce_scalar(c.data, valid, c.dictionary,
                                              agg.fn)
                    parts = (val, cnt)
                elif isinstance(agg.state_types()[0][1], T.DecimalType) \
                        and agg.state_types()[0][1].is_long:
                    from . import int128 as I
                    x = c.data if c.data.ndim == 2 else I.from_i64(c.data)
                    if agg.fn in ("sum", "avg"):
                        val = _checked_sum128(
                            x, valid, lambda p: jnp.sum(p, axis=0))
                    else:
                        val = _minmax128_scalar(x, valid, agg.fn)
                    parts = (val, cnt)
                else:
                    acc_dtype = agg.state_types()[0][1].storage_dtype
                    x = c.data.astype(acc_dtype)
                    if agg.fn in ("sum", "avg"):
                        val = jnp.sum(jnp.where(valid, x, jnp.zeros_like(x)))
                    elif agg.fn == "min":
                        val = jnp.min(jnp.where(valid, x, _max_sentinel(acc_dtype)))
                    else:
                        val = jnp.max(jnp.where(valid, x, _min_sentinel(acc_dtype)))
                    parts = (val, cnt)
        vd = None
        if agg.fn in ("min", "max") and agg.input is not None:
            if mode in ("final", "merge"):
                vd = cols[0].dictionary
            else:
                vd = batch.columns[agg.input].dictionary
        if mode in ("partial", "merge"):
            for (fname, ftype), arr in zip(agg.state_types(), parts):
                out_fields.append((fname, ftype))
                out_cols.append(Column(
                    ftype, pad(arr, ftype.storage_dtype), out_mask,
                    vd if ftype.is_string else None))
        else:
            if agg.fn in ("count", "count_star"):
                data, valid = parts[0], jnp.asarray(True)
            else:
                data, valid = _finalize_scalar(agg, parts)
            name = agg.name or agg.fn
            out_fields.append((name, agg.output_type))
            dt = agg.output_type.storage_dtype
            out_cols.append(Column(
                agg.output_type, pad(data, dt),
                jnp.zeros(cap, dtype=bool).at[0].set(valid),
                vd if agg.output_type.is_string else None))
    return Batch(Schema(out_fields), out_cols, out_mask)


def _minmax128_scalar(x: jnp.ndarray, live: jnp.ndarray,
                      fn: str) -> jnp.ndarray:
    """Global min/max over int128 limb tiles [n, 2] -> [2]."""
    return _minmax128(x, live, _GlobalReducer(), fn)


def _finalize_scalar(agg: AggSpec, parts):
    if agg.fn in _VARIANCE_FNS:
        return _variance_out(agg, *parts)
    val, cnt = parts
    valid = cnt > 0
    if agg.fn in ("bool_and", "bool_or"):
        return val > 0, valid
    if val.ndim == 1 and val.shape == (2,) \
            and agg.fn in ("sum", "avg", "min", "max") \
            and isinstance(agg.state_types()[0][1], T.DecimalType) \
            and agg.state_types()[0][1].is_long:
        return _finalize_dec128(agg, val, cnt), valid
    if agg.fn == "avg":
        if isinstance(agg.output_type, T.DecimalType):
            den = jnp.maximum(cnt, 1)
            out = (jnp.sign(val) * jnp.floor(jnp.abs(val) / den + 0.5)).astype(jnp.int64)
            return out, valid
        return val / jnp.maximum(cnt, 1).astype(val.dtype), valid
    return val, valid

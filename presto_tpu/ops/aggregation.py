"""Aggregation kernels: sort + segment-reduce group-by.

The TPU-native replacement for Presto's hash aggregation stack (reference
presto-main/.../operator/HashAggregationOperator.java:48,
MultiChannelGroupByHash.java, aggregation/builder/
InMemoryHashAggregationBuilder.java): instead of an open-addressing hash
table over channels, we sort rows by their group keys (lexicographic
``lax.sort``), detect segment boundaries, assign dense group ids by prefix
sum, and run ``jax.ops.segment_*`` reductions — everything static-shape and
branch-free on the VPU. NULL is a group key value like any other (SQL GROUP
BY semantics), encoded as a leading null-rank sort operand.

Two-phase execution mirrors Presto's PARTIAL/FINAL split (reference
AggregationNode.Step): partial emits state columns (sum+count, min+count...),
final re-aggregates states after an exchange. States are ordinary columns, so
the exchange layer needs no special serialization.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..batch import Batch, Column, Schema
from ..types import Type

_SUPPORTED = ("sum", "count", "count_star", "min", "max", "avg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: fn over an input column (None for count(*))."""

    fn: str
    input: Optional[int]          # column index in the input batch
    output_type: Type
    name: str = ""                # output column name

    def __post_init__(self):
        assert self.fn in _SUPPORTED, self.fn

    # state layout produced by partial mode / consumed by final mode
    def state_types(self) -> List[Tuple[str, Type]]:
        base = self.name or self.fn
        if self.fn in ("count", "count_star"):
            return [(f"{base}$cnt", T.BIGINT)]
        if self.fn == "avg":
            return [(f"{base}$sum", self._sum_type()), (f"{base}$cnt", T.BIGINT)]
        return [(f"{base}$val", self._sum_type() if self.fn == "sum" else self.output_type),
                (f"{base}$cnt", T.BIGINT)]

    def _sum_type(self) -> Type:
        if self.fn == "avg":
            # avg accumulates in the input/widened domain
            return self.output_type if not isinstance(self.output_type, T.DecimalType) \
                else T.DecimalType(18, self.output_type.scale)
        return self.output_type


def _group_sort(batch: Batch, group_indices: Sequence[int]):
    """Sort rows by group keys; return (key_operands, permuted batch arrays).

    Returns (sorted_cols, sorted_validity, sorted_mask, boundary, group_id,
    num_groups) where boundary marks the first live row of each group.
    """
    dead_rank = jnp.where(batch.row_mask, 0, 1).astype(jnp.int32)
    key_ops: List[jnp.ndarray] = [dead_rank]
    for gi in group_indices:
        c = batch.columns[gi]
        data = c.data
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        key_ops.append(jnp.where(c.validity, 0, 1).astype(jnp.int32))  # nulls last
        # neutralize NULL rows' data so stale values can't split NULL groups
        key_ops.append(jnp.where(c.validity, data, jnp.zeros_like(data)))
    payload: List[jnp.ndarray] = [batch.row_mask]
    for c in batch.columns:
        payload.append(c.data)
        payload.append(c.validity)
    out = jax.lax.sort(key_ops + payload, num_keys=len(key_ops), is_stable=True)
    s_keys = out[1:len(key_ops)]          # sorted key operands (minus dead rank)
    s_mask = out[len(key_ops)]
    s_data = out[len(key_ops) + 1::2]
    s_valid = out[len(key_ops) + 2::2]

    # boundary: live row whose keys differ from the previous row (or row 0)
    diff = jnp.zeros_like(s_mask)
    for op in s_keys:
        prev = jnp.roll(op, 1)
        diff = diff | (op != prev)
    first = jnp.zeros_like(s_mask).at[0].set(True)
    boundary = s_mask & (diff | first)
    group_id = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    group_id = jnp.maximum(group_id, 0)
    num_groups = jnp.sum(boundary.astype(jnp.int64))
    return s_data, s_valid, s_mask, boundary, group_id, num_groups


def _segment_aggs(
    aggs: Sequence[AggSpec],
    col_data: Sequence[jnp.ndarray],
    col_valid: Sequence[jnp.ndarray],
    mask: jnp.ndarray,
    group_id: jnp.ndarray,
    cap: int,
    from_states: bool,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Per-aggregate (value_arrays...) segment reductions.

    Returns, per agg, a list of (data, counts-ish) arrays matching its state
    layout when ``from_states`` is False, or merged states when True.
    """
    results = []
    state_cursor = 0
    for agg in aggs:
        if from_states:
            # inputs are state columns in layout order
            n_state = len(agg.state_types())
            s_cols = list(range(state_cursor, state_cursor + n_state))
            state_cursor += n_state
            if agg.fn in ("count", "count_star"):
                cnt_in = jnp.where(mask, col_data[s_cols[0]], 0)
                cnt = jax.ops.segment_sum(cnt_in, group_id, num_segments=cap)
                results.append((cnt,))
                continue
            val_in = col_data[s_cols[0]]
            cnt_raw = col_data[s_cols[1]]
            cnt_in = jnp.where(mask, cnt_raw, 0)
            cnt = jax.ops.segment_sum(cnt_in, group_id, num_segments=cap)
            live = mask & (cnt_raw > 0)
            if agg.fn in ("sum", "avg"):
                contrib = jnp.where(live, val_in, jnp.zeros_like(val_in))
                val = jax.ops.segment_sum(contrib, group_id, num_segments=cap)
            elif agg.fn == "min":
                sent = _max_sentinel(val_in.dtype)
                contrib = jnp.where(live, val_in, sent)
                val = jax.ops.segment_min(contrib, group_id, num_segments=cap)
            else:  # max
                sent = _min_sentinel(val_in.dtype)
                contrib = jnp.where(live, val_in, sent)
                val = jax.ops.segment_max(contrib, group_id, num_segments=cap)
            results.append((val, cnt))
            continue
        # raw-input mode
        if agg.fn == "count_star":
            cnt = jax.ops.segment_sum(
                mask.astype(jnp.int64), group_id, num_segments=cap)
            results.append((cnt,))
            continue
        data = col_data[agg.input]
        valid = col_valid[agg.input] & mask
        cnt = jax.ops.segment_sum(valid.astype(jnp.int64), group_id, num_segments=cap)
        if agg.fn == "count":
            results.append((cnt,))
            continue
        acc_t = agg.state_types()[0][1]
        acc_dtype = acc_t.storage_dtype
        x = data.astype(acc_dtype)
        if agg.fn in ("sum", "avg"):
            if isinstance(acc_t, T.DecimalType) and isinstance(agg.output_type, T.DecimalType):
                pass  # same scale accumulate
            contrib = jnp.where(valid, x, jnp.zeros_like(x))
            val = jax.ops.segment_sum(contrib, group_id, num_segments=cap)
        elif agg.fn == "min":
            contrib = jnp.where(valid, x, _max_sentinel(acc_dtype))
            val = jax.ops.segment_min(contrib, group_id, num_segments=cap)
        else:
            contrib = jnp.where(valid, x, _min_sentinel(acc_dtype))
            val = jax.ops.segment_max(contrib, group_id, num_segments=cap)
        results.append((val, cnt))
    return results


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype=dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype=dtype)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype=dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype=dtype)


def _finalize(agg: AggSpec, parts: Tuple[jnp.ndarray, ...]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """state -> (output data, output validity)."""
    if agg.fn in ("count", "count_star"):
        return parts[0], jnp.ones_like(parts[0], dtype=bool)
    val, cnt = parts
    valid = cnt > 0
    if agg.fn == "avg":
        if isinstance(agg.output_type, T.DecimalType):
            den = jnp.maximum(cnt, 1)
            q = val / den
            out = (jnp.sign(q) * jnp.floor(jnp.abs(val) / den + 0.5)).astype(jnp.int64)
            return out, valid
        den = jnp.maximum(cnt, 1).astype(val.dtype)
        return val / den, valid
    out = val.astype(agg.output_type.storage_dtype)
    return out, valid


def grouped_aggregate(
    batch: Batch,
    group_indices: Sequence[int],
    aggs: Sequence[AggSpec],
    mode: str = "single",
    output_capacity: Optional[int] = None,
) -> Batch:
    """GROUP BY aggregation. mode: 'single' | 'partial' | 'final' | 'merge'.

    In 'final' and 'merge' modes the input batch layout must be
    [group key columns..., state columns in agg order...] — i.e. the output
    layout of 'partial' mode (possibly concatenated/exchanged in between).
    'merge' re-combines state rows sharing a key but keeps the state layout
    (Presto's intermediate combine step), enabling hierarchical merging.
    """
    assert mode in ("single", "partial", "final", "merge")
    cap = output_capacity or batch.capacity
    s_data, s_valid, s_mask, boundary, group_id, num_groups = _group_sort(
        batch, group_indices)

    # group key output: gather the first row of each segment
    bidx = jnp.nonzero(boundary, size=cap, fill_value=batch.capacity - 1)[0]
    out_mask = jnp.arange(cap) < num_groups
    key_cols = []
    for gi in group_indices:
        c = batch.columns[gi]
        key_cols.append(Column(
            c.type,
            jnp.take(s_data[gi], bidx, axis=0),
            jnp.take(s_valid[gi], bidx, axis=0) & out_mask,
            c.dictionary,
        ))

    from_states = mode in ("final", "merge")
    if from_states:
        n_keys = len(group_indices)
        state_data = s_data[n_keys:]
        seg = _segment_aggs(aggs, state_data, s_valid[n_keys:], s_mask,
                            group_id, cap, from_states=True)
    else:
        seg = _segment_aggs(aggs, s_data, s_valid, s_mask, group_id, cap,
                            from_states=False)

    out_cols: List[Column] = list(key_cols)
    out_fields: List[Tuple[str, Type]] = [
        (batch.schema.names[gi], batch.schema.types[gi]) for gi in group_indices
    ]
    if mode in ("partial", "merge"):
        for agg, parts in zip(aggs, seg):
            for (fname, ftype), arr in zip(agg.state_types(), parts):
                out_fields.append((fname, ftype))
                out_cols.append(Column(
                    ftype, arr.astype(ftype.storage_dtype), out_mask, None))
    else:
        for agg, parts in zip(aggs, seg):
            data, valid = _finalize(agg, parts)
            name = agg.name or agg.fn
            out_fields.append((name, agg.output_type))
            out_cols.append(Column(
                agg.output_type, data.astype(agg.output_type.storage_dtype),
                valid & out_mask, None))
    return Batch(Schema(out_fields), out_cols, out_mask)


def global_aggregate(
    batch: Batch, aggs: Sequence[AggSpec], mode: str = "single"
) -> Batch:
    """Aggregation without GROUP BY: one output row, even over empty input
    (reference AggregationOperator.java global aggregation semantics).
    'merge' consumes state columns and emits merged state columns."""
    assert mode in ("single", "partial", "final", "merge")
    cap = 128  # minimum bucket; one live row
    mask = batch.row_mask
    out_fields: List[Tuple[str, Type]] = []
    out_cols: List[Column] = []
    out_mask = jnp.arange(cap) < 1

    def pad(scalar, dtype):
        return jnp.zeros(cap, dtype=dtype).at[0].set(scalar.astype(dtype))

    state_cursor = 0
    for agg in aggs:
        if mode in ("final", "merge"):
            n_state = len(agg.state_types())
            cols = batch.columns[state_cursor:state_cursor + n_state]
            state_cursor += n_state
            if agg.fn in ("count", "count_star"):
                cnt = jnp.sum(jnp.where(mask, cols[0].data, 0))
                parts: Tuple[jnp.ndarray, ...] = (cnt,)
            else:
                cnt_raw = cols[1].data
                live = mask & (cnt_raw > 0)
                cnt = jnp.sum(jnp.where(mask, cnt_raw, 0))
                v = cols[0].data
                if agg.fn in ("sum", "avg"):
                    val = jnp.sum(jnp.where(live, v, jnp.zeros_like(v)))
                elif agg.fn == "min":
                    val = jnp.min(jnp.where(live, v, _max_sentinel(v.dtype)))
                else:
                    val = jnp.max(jnp.where(live, v, _min_sentinel(v.dtype)))
                parts = (val, cnt)
        else:
            if agg.fn == "count_star":
                parts = (jnp.sum(mask.astype(jnp.int64)),)
            else:
                c = batch.columns[agg.input]
                valid = c.validity & mask
                cnt = jnp.sum(valid.astype(jnp.int64))
                if agg.fn == "count":
                    parts = (cnt,)
                else:
                    acc_dtype = agg.state_types()[0][1].storage_dtype
                    x = c.data.astype(acc_dtype)
                    if agg.fn in ("sum", "avg"):
                        val = jnp.sum(jnp.where(valid, x, jnp.zeros_like(x)))
                    elif agg.fn == "min":
                        val = jnp.min(jnp.where(valid, x, _max_sentinel(acc_dtype)))
                    else:
                        val = jnp.max(jnp.where(valid, x, _min_sentinel(acc_dtype)))
                    parts = (val, cnt)
        if mode in ("partial", "merge"):
            for (fname, ftype), arr in zip(agg.state_types(), parts):
                out_fields.append((fname, ftype))
                out_cols.append(Column(ftype, pad(arr, ftype.storage_dtype),
                                       out_mask, None))
        else:
            if agg.fn in ("count", "count_star"):
                data, valid = parts[0], jnp.asarray(True)
            else:
                data, valid = _finalize_scalar(agg, parts)
            name = agg.name or agg.fn
            out_fields.append((name, agg.output_type))
            dt = agg.output_type.storage_dtype
            out_cols.append(Column(
                agg.output_type, pad(data, dt),
                jnp.zeros(cap, dtype=bool).at[0].set(valid), None))
    return Batch(Schema(out_fields), out_cols, out_mask)


def _finalize_scalar(agg: AggSpec, parts):
    val, cnt = parts
    valid = cnt > 0
    if agg.fn == "avg":
        if isinstance(agg.output_type, T.DecimalType):
            den = jnp.maximum(cnt, 1)
            out = (jnp.sign(val) * jnp.floor(jnp.abs(val) / den + 0.5)).astype(jnp.int64)
            return out, valid
        return val / jnp.maximum(cnt, 1).astype(val.dtype), valid
    return val, valid

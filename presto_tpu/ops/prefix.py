"""Prefix-sum helper that sidesteps XLA's cumsum compile blowup.

``jnp.cumsum`` lowers through reduce-window, whose compile time explodes
with array size on both backends used here (measured: 252s to compile a
single f64 cumsum at 2^17 on XLA:CPU; 528s cold for i64 at 2^26 on the
TPU backend — docs/perf.md). ``jax.lax.associative_scan`` lowers to a
O(log n) slice/add ladder instead and compiles in seconds at the same
shapes, with identical results for integer dtypes (integer addition is
associative) and a reassociated-but-order-independent sum for floats —
SQL aggregate semantics define no evaluation order, and every consumer
here (group ids, run boundaries, window running sums, coverage counts)
either uses integers or tolerates float reassociation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def prefix_sum(x: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Inclusive prefix sum along ``axis`` (drop-in for jnp.cumsum)."""
    return jax.lax.associative_scan(jnp.add, x, axis=axis)

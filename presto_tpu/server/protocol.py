"""HTTP statement protocol: the client-facing front door.

Wire-compatible (for the paths a basic client uses) with the reference's
statement protocol (reference presto-client/.../StatementClientV1.java:147
POSTs /v1/statement then polls ``nextUri`` :339 until it disappears;
dispatcher/QueuedStatementResource.java:146-167 and
server/protocol/ExecutingStatementResource.java:147 serve it):

- ``POST /v1/statement`` with the SQL body and X-Presto-* session headers
  returns a QueryResults JSON document whose ``nextUri`` pages through
  results;
- ``GET  /v1/statement/executing/{id}/{slug}/{token}`` returns columns +
  a data page + the next ``nextUri`` (absent on the final page);
- ``DELETE /v1/statement/executing/{id}/{slug}/{token}`` cancels;
- session mutations round-trip through response headers
  (X-Presto-Set-Session / X-Presto-Clear-Session — reference
  client/PrestoHeaders.java:30-31), keeping the server stateless about
  client session state.

Queries execute on a LocalRunner in a worker thread; pages stream from a
bounded queue — the role of the coordinator's per-query output buffer
(reference server/protocol/Query.java:99 pulling via ExchangeClient).
"""
from __future__ import annotations

import collections
import datetime
import json
import math
import os
import queue
import secrets
import threading
import time
import urllib.parse
from decimal import Decimal

from .._devtools.lockcheck import checked_lock
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

ROWS_PER_PAGE = 4096


def _json_value(v):
    if v is None or isinstance(v, (int, float, str, bool)):
        if isinstance(v, float) and not math.isfinite(v):
            return str(v)
        return v
    if hasattr(v, "item"):            # numpy scalar
        return _json_value(v.item())
    if isinstance(v, datetime.date):
        return v.isoformat()
    if isinstance(v, Decimal):
        return str(v)
    return str(v)


def _runner_accepts_serving(runner) -> bool:
    import inspect
    try:
        return "serving" in inspect.signature(
            runner.execute).parameters
    except (TypeError, ValueError):
        return False


class _ProducerPool:
    """Shared daemon worker pool for statement producers. A serving
    fleet at hundreds of statements/sec paid a fresh thread spawn per
    query (~100µs of pure GIL churn on a ~1ms cache hit); workers here
    are reused and spawn lazily up to the cap. Tasks beyond the cap
    queue — safe, because a producer blocked in admission is woken by a
    grant from a RUNNING producer finishing, never by a task that has
    yet to start. Daemon threads, like the per-query threads they
    replace: interpreter exit never hangs on an abandoned statement."""

    def __init__(self, cap: int = 256):
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._cap = cap
        self._threads = 0
        self._idle = 0
        self._lock = checked_lock("protocol.producers")

    def submit(self, fn) -> None:
        self._q.put(fn)
        with self._lock:
            if self._idle == 0 and self._threads < self._cap:
                self._threads += 1
                threading.Thread(target=self._worker,
                                 daemon=True).start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn = self._q.get()
            finally:
                with self._lock:
                    self._idle -= 1
            try:
                fn()
            except Exception:
                pass                 # _run reports its own errors


_PRODUCERS = _ProducerPool()


class _InlinePages:
    """Page channel for the inline lane. The producer runs to
    completion in the consumer's own thread before any reader can
    exist (``_Query.__init__`` calls ``_run()`` synchronously), so a
    plain deque replaces ``queue.Queue`` — six threading-primitive
    constructions plus a lock round-trip per put/get, per statement,
    on the hottest path. Classic paging from another handler thread
    after the POST returned is still safe: the deque is fully
    populated before the response is written, and deque append/popleft
    are atomic."""

    __slots__ = ("_d",)

    def __init__(self):
        self._d: collections.deque = collections.deque()

    def put(self, item, timeout=None) -> None:
        self._d.append(item)

    def get(self, timeout=None):
        return self.get_nowait()

    def get_nowait(self):
        try:
            return self._d.popleft()
        except IndexError:
            raise queue.Empty from None


class _Query:
    """One running statement: executes on the producer pool, pages
    buffered."""

    def __init__(self, qid: str, slug: str, sql: str, runner,
                 session_overrides: Dict[str, str],
                 admission=None, user: str = "",
                 accepts_serving: Optional[bool] = None,
                 inline: bool = False):
        self.user = user
        self.id = qid
        self.slug = slug
        self.sql = sql
        self._admission = admission
        # serving-plane handoff (group memory account + scheduler
        # share) rides runner.execute(serving=...) when the runner
        # supports it; protocol doubles in tests may not. The server
        # probes its runner ONCE (an invariant — not per statement).
        self._accepts_serving = (_runner_accepts_serving(runner)
                                 if accepts_serving is None
                                 else accepts_serving)
        self.state = "QUEUED"
        self.error: Optional[Dict] = None
        self.columns: Optional[List[Dict]] = None
        self.set_session: Dict[str, str] = {}
        self.clear_session: List[str] = []
        self._inline = bool(inline and (admission is None
                                        or admission.granted))
        # inline lane: the producer IS the consumer's thread, so a
        # bounded put could deadlock — unbounded there (rows are
        # already materialized; the buffered copy is the same order of
        # memory the async path would build). Bounded (backpressure on
        # slow pagers) on the pool path.
        self._pages = (_InlinePages() if self._inline
                       else queue.Queue(maxsize=8))
        self._next_token = 0
        self._last_page: Optional[Tuple[int, Optional[List]]] = None
        self._page_lock = checked_lock("protocol.query.pages")
        # guards state transitions: cancel() and the producer thread race,
        # and FAILED must never become FINISHED (the reference's
        # QueryStateMachine rejects transitions out of terminal states)
        self._state_lock = checked_lock("protocol.query.state")
        self._cancelled = threading.Event()
        #: set when the producer finished (every exit path) — the
        #: pool-era replacement for joining the per-query thread
        self.done = threading.Event()
        self._runner = runner
        self._overrides = session_overrides
        if self._inline:
            # inline lane: a statement the server has seen complete
            # within the fast-path grace runs in the CALLING (http
            # handler) thread when its group admits without queueing.
            # Under keep-alive the handler thread is connection-bound
            # either way, so this spends no extra thread — it erases
            # the submit->producer and page->poller wakeups, which on
            # a saturated host are two forced context switches per
            # statement.
            from ..obs.metrics import REGISTRY
            REGISTRY.counter("serving_inline_lane_total").inc()
            self._run()
        else:
            _PRODUCERS.submit(self._run)

    def _queued_timeout_override(self):
        """Per-query ``query_queued_timeout``: the client's session
        override wins, else the server session's default (both validated
        through config.SESSION_PROPERTIES)."""
        override = self._overrides.get("query_queued_timeout")
        if override is None:
            session = getattr(self._runner, "session", None)
            if session is not None:
                override = session.properties.get("query_queued_timeout")
        return override

    # -- producer ------------------------------------------------------------
    def _run(self) -> None:
        from .resource_groups import QueryQueuedTimeoutError
        serving = None
        t_submit = time.monotonic()
        try:
            # admission: block in QUEUED until the resource group grants
            # a run slot (reference dispatcher/DispatchManager.java:134 +
            # resourcegroups/InternalResourceGroup run/queue decision);
            # a deadline (queryQueuedTimeout group config /
            # query_queued_timeout session prop) fails the query with a
            # distinct verdict instead of waiting forever
            if self._admission is not None:
                timeout = self._admission.queued_timeout_s(
                    self._queued_timeout_override())
                deadline = (self._admission.submit_time + timeout
                            if timeout is not None else None)
                while not self._admission.wait(0.1):
                    if self._cancelled.is_set():
                        return
                    if deadline is not None \
                            and time.monotonic() > deadline:
                        self._admission.time_out()
                        raise QueryQueuedTimeoutError(
                            f"query exceeded its queued timeout of "
                            f"{timeout:g}s in resource group "
                            f"{self._admission.group.path!r}")
                from ..serving.groups import serving_context
                serving = serving_context(self._admission)
                # SLO latency-spike injection point (tests/chaos): a
                # sleep rule here adds user-visible serving latency, a
                # fail rule adds availability errors — both flow into
                # the per-group serving_* metrics recorded below
                from ..exec.failpoints import FAILPOINTS
                FAILPOINTS.hit("protocol.serve",
                               key=self._admission.group.path)
            self.state = "RUNNING"
            kwargs = ({"serving": serving}
                      if serving is not None and self._accepts_serving
                      else {})
            res = self._runner.execute(
                self.sql, properties=dict(self._overrides),
                user=self.user, cancel_event=self._cancelled, **kwargs)
            # the slot frees as soon as execution completes: paging
            # buffered rows out to a (possibly slow) client must not
            # hold the group's concurrency slot (the finally below is
            # the idempotent safety net for every other exit path)
            if serving is not None:
                serving.close()
            if self._admission is not None:
                self._admission.release()
            self.columns = [
                {"name": n, "type": t.display()}
                for n, t in zip(res.names, res.types)
            ]
            sql_head = self.sql.lstrip().lower()
            if sql_head.startswith("set session"):
                stmt = self.sql.lstrip()[len("set session"):].strip()
                if "=" in stmt:
                    k, v = stmt.split("=", 1)
                    self.set_session[k.strip()] = v.strip().strip("'")
            elif sql_head.startswith("reset session"):
                self.clear_session.append(
                    self.sql.lstrip()[len("reset session"):].strip())
            rows = res.rows
            for i in range(0, max(len(rows), 1), ROWS_PER_PAGE):
                if self._cancelled.is_set():
                    break
                page = [[_json_value(v) for v in r]
                        for r in rows[i:i + ROWS_PER_PAGE]]
                self._put_page(page)
            # a cancel that raced completion must keep the FAILED/
            # USER_CANCELED verdict set by cancel() (the reference's
            # QueryStateMachine refuses FAILED->FINISHED transitions)
            with self._state_lock:
                if not self._cancelled.is_set():
                    self.state = "FINISHED"
        except QueryQueuedTimeoutError as e:
            with self._state_lock:
                if not self._cancelled.is_set():
                    self.state = "FAILED"
                    self.error = {
                        "message": str(e),
                        "errorCode": 1,
                        "errorName": "QUERY_QUEUED_TIMEOUT",
                        "errorType": "INSUFFICIENT_RESOURCES",
                    }
        except Exception as e:  # surfaced as QueryError, not a 500
            with self._state_lock:
                if not self._cancelled.is_set():
                    self.state = "FAILED"
                    self.error = {
                        "message": str(e),
                        "errorCode": 1,
                        "errorName": getattr(e, "name",
                                             type(e).__name__),
                        "errorType": "USER_ERROR",
                    }
        finally:
            # admission leak fix: EVERY exit path — planning/execution
            # failure, queued timeout, cancel while queued, even an
            # unexpected paging error — releases the resource-group
            # slot exactly once (release() is idempotent) and refunds
            # residual group memory, so the group's running count
            # always returns to zero
            if serving is not None:
                serving.close()
            if self._admission is not None:
                self._admission.release()
                self._record_serving_slo(t_submit)
            self._put_page(None)      # end-of-stream sentinel
            self.done.set()

    def _record_serving_slo(self, t_submit: float) -> None:
        """Per-group SLO feed (obs/slo.py): end-to-end serving latency
        (queue wait included — that's what the tenant experiences) and
        request/error counts, keyed by the admitting group's path.
        User cancels are excluded: they are neither a latency sample
        nor an availability error the server caused."""
        with self._state_lock:
            state, error = self.state, self.error
        if state not in ("FINISHED", "FAILED"):
            return              # cancelled while queued, never served
        if error is not None and error.get("errorName") == "USER_CANCELED":
            return
        from ..obs.metrics import REGISTRY
        path = self._admission.group.path
        REGISTRY.counter(f"serving_requests_total.{path}").inc()
        if state == "FAILED":
            REGISTRY.counter(f"serving_errors_total.{path}").inc()
        REGISTRY.histogram(f"serving_latency_seconds.{path}").observe(
            time.monotonic() - t_submit)

    def _put_page(self, page) -> None:
        """Bounded put that gives up if the query is cancelled (a cancel
        with no consumer left must not pin the producer thread)."""
        while not self._cancelled.is_set():
            try:
                self._pages.put(page, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer ------------------------------------------------------------
    def poll_page(self, token: int, timeout: float):
        """``next_page`` bounded by ``timeout``: (True, page) when the
        page arrived in time, (False, None) otherwise — the statement
        POST uses it to inline a fast query's results into the first
        response instead of sending the client back for two more round
        trips (a result-cache hit answers in ~a millisecond; the extra
        GETs would triple its served latency)."""
        deadline = time.monotonic() + timeout
        with self._page_lock:
            if self._last_page is not None \
                    and self._last_page[0] == token:
                return True, self._last_page[1]
            if token != self._next_token:
                raise KeyError(f"token {token} is gone")
            while True:
                if self._cancelled.is_set():
                    page = None
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False, None
                try:
                    page = self._pages.get(timeout=min(remaining, 0.1))
                    break
                except queue.Empty:
                    if self._inline:
                        # the inline producer already ran to completion;
                        # an empty channel means no page is ever coming
                        return False, None
                    continue
            self._last_page = (token, page)
            self._next_token = token + 1
            return True, page

    def next_page(self, token: int):
        """Page for ``token``; the last token may be re-requested (the
        reference protocol's restartable token semantics). Serialized:
        a client retry racing its own original request must not consume
        two pages. Exactly :meth:`poll_page` with no deadline — ONE
        implementation owns the token/replay/cancel invariants."""
        return self.poll_page(token, float("inf"))[1]

    def cancel(self) -> None:
        with self._state_lock:
            if self.state in ("FINISHED", "FAILED"):
                # terminal states stay put: clients routinely DELETE the
                # statement URI on close after draining all pages, and a
                # completed query must not re-report as canceled
                return
            self._cancelled.set()
            self.state = "FAILED"
            self.error = {"message": "Query was canceled", "errorCode": 1,
                          "errorName": "USER_CANCELED",
                          "errorType": "USER_ERROR"}
        while True:                   # unblock/starve the producer
            try:
                self._pages.get_nowait()
            except queue.Empty:
                break


#: single-page query console (the role of the reference's React webapp,
#: presto-main/src/main/resources/webapp/index.html query list — one
#: dependency-free page polling /v1/query)
_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>presto-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#16181d;
      color:#e8e8e8}
 h1{font-size:1.2rem} table{border-collapse:collapse;width:100%}
 th,td{text-align:left;padding:.35rem .6rem;border-bottom:1px solid #333;
       font-size:.85rem} th{color:#9aa}
 td.sql{font-family:ui-monospace,monospace;white-space:pre-wrap;
        word-break:break-word;max-width:48rem}
 .FINISHED{color:#6c6}.FAILED{color:#e66}.RUNNING{color:#fd5}
 .muted{color:#789;font-size:.8rem}
</style></head><body>
<h1>presto-tpu &mdash; queries</h1>
<div class="muted" id="meta"></div>
<table><thead><tr><th>id</th><th>state</th><th>elapsed</th><th>query</th>
</tr></thead><tbody id="rows"></tbody></table>
<h1 id="dtitle" style="display:none">detail</h1>
<div id="detail"></div>
<script>
function esc(s){return s.replace(/&/g,'&amp;').replace(/</g,'&lt;');}
async function refresh(){
  const r = await fetch('/v1/query');
  const qs = await r.json();
  document.getElementById('meta').textContent =
    qs.length + ' queries \\u00b7 refreshed ' +
    new Date().toLocaleTimeString();
  document.getElementById('rows').innerHTML = qs.map(q =>
    '<tr><td><a href="#" style="color:#8cf" onclick="show(\\''+q.queryId+
    '\\');return false">'+q.queryId+'</a></td><td class="'+q.state+'">'+
    q.state+'</td><td>'+q.elapsedMs+'ms</td><td class="sql">'+
    esc(q.query)+'</td></tr>').join('');
}
async function show(id){
  // per-node timeline: proportional wall-time bars + split completions
  // (the reference webapp's stage/timeline pages)
  const q = await (await fetch('/v1/query/'+id)).json();
  const mx = Math.max(1, ...q.nodes.map(n=>n.wallMs));
  document.getElementById('dtitle').style.display='block';
  document.getElementById('dtitle').textContent =
    id+' \\u2014 '+q.state+' ('+q.elapsedMs+'ms)';
  document.getElementById('detail').innerHTML =
    '<table><thead><tr><th>operator</th><th>wall</th><th>batches</th>'+
    '<th></th></tr></thead><tbody>'+
    q.nodes.map(n=>'<tr><td>'+esc(n.node)+'</td><td>'+n.wallMs+
      'ms</td><td>'+n.batches+'</td><td><div style="background:#48f;'+
      'height:.6rem;width:'+Math.round(240*n.wallMs/mx)+
      'px"></div></td></tr>').join('')+'</tbody></table>'+
    (q.splits.length ? '<p class="muted">'+q.splits.length+
      ' splits: '+q.splits.map(s=>esc(s.table)+'#'+s.split+' '+
      s.wallMs+'ms').join(' \\u00b7 ')+'</p>' : '');
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class _FastHeaders:
    """Case-insensitive read-only header mapping — the slice of
    ``email.message.Message`` this server consumes (``.get`` with a
    default). Keys are stored lower-cased by :meth:`_Handler.parse_request`."""

    __slots__ = ("_d",)

    def __init__(self, d: Dict[str, str]):
        self._d = d

    def get(self, name: str, default=None):
        return self._d.get(name.lower(), default)

    def __getitem__(self, name: str) -> str:
        v = self._d.get(name.lower())
        if v is None:
            raise KeyError(name)
        return v

    def __contains__(self, name) -> bool:
        return isinstance(name, str) and name.lower() in self._d

    def items(self):
        return list(self._d.items())


class _Handler(BaseHTTPRequestHandler):
    server_version = "presto-tpu"
    protocol_version = "HTTP/1.1"
    # result-cache hits answer in ~a millisecond; without TCP_NODELAY
    # the kernel's delayed-ACK/Nagle interaction quantizes every small
    # response at ~40ms — two orders of magnitude over the engine time
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):   # silence request logging
        pass

    def parse_request(self) -> bool:
        """Drop-in for ``BaseHTTPRequestHandler.parse_request`` with
        the header block parsed by a plain line loop instead of the
        email package (``http.client.parse_headers`` routes every
        request through the MIME feedparser — a measurable slice of a
        warm cache-hit statement's handler CPU). Same request-line,
        close/keep-alive, Expect, and limit semantics; headers land in
        a :class:`_FastHeaders` (case-insensitive ``.get``, the only
        surface this server uses)."""
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline,
                          "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if not words:
            return False
        if len(words) >= 3:
            version = words[-1]
            if version == "HTTP/1.1":
                # the only version real clients send here
                self.close_connection = False
            else:
                try:
                    if not version.startswith("HTTP/"):
                        raise ValueError
                    base = version.split("/", 1)[1]
                    nums = base.split(".")
                    if len(nums) != 2:
                        raise ValueError
                    vnum = int(nums[0]), int(nums[1])
                except (ValueError, IndexError):
                    self.send_error(
                        400, "Bad request version (%r)" % version)
                    return False
                if vnum >= (2, 0):
                    self.send_error(
                        505, "Invalid HTTP version (%s)" % base)
                    return False
                if vnum >= (1, 1) \
                        and self.protocol_version >= "HTTP/1.1":
                    self.close_connection = False
            self.request_version = version
        if not 2 <= len(words) <= 3:
            self.send_error(
                400, "Bad request syntax (%r)" % requestline)
            return False
        command, path = words[:2]
        if len(words) == 2:
            self.close_connection = True
            if command != "GET":
                self.send_error(
                    400, "Bad HTTP/0.9 request type (%r)" % command)
                return False
        self.command, self.path = command, path
        if self.path.startswith("//"):
            # gh-87389: collapse leading // (open-redirect hardening,
            # mirrored from the stock parser)
            self.path = "/" + self.path.lstrip("/")
        hdrs: Dict[str, str] = {}
        last: Optional[str] = None
        while True:
            line = self.rfile.readline(65537)
            if len(line) > 65536:
                self.send_error(431, "Line too long")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            if len(hdrs) >= 100:
                self.send_error(431, "Too many headers")
                return False
            text = line.decode("iso-8859-1").rstrip("\r\n")
            if text[:1] in (" ", "\t") and last is not None:
                # obs-fold continuation line
                hdrs[last] += " " + text.strip()
                continue
            key, sep, value = text.partition(":")
            if not sep:
                continue        # tolerated, like the email parser
            last = key.strip().lower()
            hdrs[last] = value.strip()
        self.headers = _FastHeaders(hdrs)
        conntype = hdrs.get("connection", "").lower()
        if conntype == "close":
            self.close_connection = True
        elif (conntype == "keep-alive"
                and self.protocol_version >= "HTTP/1.1"):
            self.close_connection = False
        expect = hdrs.get("expect", "").lower()
        if (expect == "100-continue"
                and self.protocol_version >= "HTTP/1.1"
                and self.request_version >= "HTTP/1.1"):
            if not self.handle_expect_100():
                return False
        return True

    @property
    def _srv(self) -> "PrestoTpuServer":
        return self.server.presto       # type: ignore[attr-defined]

    #: (whole second, rendered Date header value) — every response
    #: within one second shares the strftime work
    _date_cache: Tuple[int, str] = (0, "")
    _version_cache: str = ""

    def _reply(self, code: int, doc: Dict,
               headers: Optional[Dict[str, str]] = None) -> None:
        # hand-composed response in ONE wfile.write: the wfile of a
        # BaseHTTPRequestHandler is unbuffered, so the stock
        # send_response/.../end_headers + body sequence costs two
        # sendall syscalls (and two TCP segments) per response — on
        # the serving hot path that is measurable against a ~1ms
        # statement
        body = json.dumps(doc).encode()
        now = int(time.time())
        date = _Handler._date_cache
        if date[0] != now:
            date = (now, self.date_time_string(now))
            _Handler._date_cache = date
        if not _Handler._version_cache:
            _Handler._version_cache = self.version_string()
        status = self.responses.get(code, ("", ""))[0]
        head = (f"HTTP/1.1 {code} {status}\r\n"
                f"Server: {_Handler._version_cache}\r\n"
                f"Date: {date[1]}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n")
        for k, v in (headers or {}).items():
            head += f"{k}: {v}\r\n"
        if self.close_connection:
            head += "Connection: close\r\n"
        self.wfile.write(head.encode("latin-1") + b"\r\n" + body)

    def do_POST(self) -> None:
        if self.path == "/v1/announce":
            # node-internal announcement (reference discovery service);
            # not behind client auth, like reference internal comms
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n) or b"{}")
            self._srv.discovery.announce(doc.get("nodeId", ""),
                                         doc.get("uri", ""),
                                         doc.get("state", "ACTIVE"),
                                         doc.get("role", "worker"))
            self._reply(202, {"announced": True})
            return
        if self.path in ("/v1/fleet/bump", "/v1/fleet/heartbeat"):
            # coordinator-to-coordinator plane (serving/fleet.py):
            # write bumps keep peer caches coherent, heartbeats carry
            # federated resource-group counts. Node-internal like
            # /v1/announce — not behind client auth. 404 when this
            # server is not a fleet member.
            fleet = self._srv.fleet
            if fleet is None:
                self._reply(404, {"error": "not a fleet member"})
                return
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n) or b"{}")
            if self.path.endswith("/bump"):
                folded = fleet.fold_bump(doc)
                self._reply(200, {"folded": bool(folded)})
            else:
                fleet.fold_heartbeat(doc)
                self._reply(200, {"ok": True})
            return
        if self.path != "/v1/statement":
            self._reply(404, {"error": "not found"})
            return
        if self._srv.shutting_down:
            # drain window (reference server/GracefulShutdownHandler on
            # the coordinator): running statements page out normally,
            # new ones are refused so a rolling restart never strands a
            # client mid-queue
            self._reply(503, {"error": "coordinator is shutting down"})
            return
        if not self._authenticate():
            return
        n = int(self.headers.get("Content-Length", 0))
        sql = self.rfile.read(n).decode()
        overrides = {}
        for part in (self.headers.get("X-Presto-Session") or "").split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                overrides[k.strip()] = urllib.parse.unquote(v.strip())
        from .resource_groups import QueryQueueFullError
        try:
            q = self._srv.create_query(
                sql, overrides,
                user=getattr(self, "_auth_user", None)
                or self.headers.get("X-Presto-User", ""),
                source=self.headers.get("X-Presto-Source", ""),
                inline=(self._srv._inline_lane
                        and sql in self._srv._fast_sql))
        except QueryQueueFullError as e:
            self._reply(429, {"error": {"message": str(e),
                                        "errorName": "QUERY_QUEUE_FULL",
                                        "errorType": "INSUFFICIENT_RESOURCES"}})
            return
        # single-round-trip fast path: wait briefly for the first page
        # and inline it (plus the end-of-stream sentinel when the query
        # already drained) — a cache-hit statement completes in ~1ms,
        # and serving it in ONE http exchange instead of three is the
        # difference between protocol-bound and engine-bound QPS.
        # Slow/queued queries fall back to the classic paging doc after
        # the grace.
        try:
            ok, page = q.poll_page(0, 0.05)
        except KeyError:
            ok, page = False, None
        if not ok:
            # exceeded the grace: classic paging, and the statement
            # loses its inline-lane seat until it proves fast again
            self._srv.note_fast_statement(sql, False)
            self._reply(200, self._results_doc(q, 0, first=True))
            return
        token = 0
        if page is not None:
            # try to fold in the terminal sentinel (single-page result)
            try:
                ok2, page2 = q.poll_page(1, 0.005)
            except KeyError:
                ok2, page2 = False, None
            if ok2 and page2 is not None:
                page = page + page2
                token = 1
                # don't chase further pages: hand off to normal paging
                self._srv.note_fast_statement(sql, False)
            elif ok2:
                doc = self._results_doc(q, token, page=page)
                doc.pop("nextUri", None)       # stream fully drained
                if q.error is not None:
                    # failed AFTER emitting rows (e.g. mid-paging):
                    # folding the sentinel must not swallow the verdict
                    # the classic GET path would have delivered
                    doc["error"] = q.error
                else:
                    self._srv.note_fast_statement(sql, True)
                self._reply(200, doc, self._session_headers(q))
                return
        if page is None and q.error is None:
            # sentinel on the first poll: a zero-page statement that
            # drained within the grace — inline-lane eligible too
            self._srv.note_fast_statement(sql, True)
        self._reply(200, self._results_doc(q, token, page=page),
                    self._session_headers(q))

    def do_GET(self) -> None:
        if self.path == "/v1/service":
            self._reply(200, {"services": self._srv.discovery.nodes()})
            return
        if self.path.split("?")[0].rstrip("/") == "/v1/info":
            # lifecycle surface, symmetric with the worker's: load
            # balancers / rolling-restart tooling watch the state flip
            # to SHUTTING_DOWN and drain traffic away
            self._reply(200, {
                "nodeId": (self._srv.fleet.node_id
                           if self._srv.fleet is not None
                           else "coordinator"),
                "state": ("SHUTTING_DOWN" if self._srv.shutting_down
                          else "ACTIVE"),
                "queries": {
                    "RUNNING": sum(
                        1 for q in list(self._srv.queries.values())
                        if q.state in ("QUEUED", "RUNNING"))},
            })
            return
        if self.path.rstrip("/") == "/v1/fleet":
            # fleet membership status (node-internal plane, like
            # /v1/service): peers, bump seq, remote group counts + ages
            fleet = self._srv.fleet
            if fleet is None:
                self._reply(404, {"error": "not a fleet member"})
                return
            self._reply(200, fleet.status())
            return
        if self.path.rstrip("/") == "/v1/autoscale":
            # elasticity controller status (node-internal plane, like
            # /v1/slo): policy, worker set, confirmation streaks, and
            # the last control tick's decisions/applied/blocked
            ctl = self._srv.autoscaler
            if ctl is None:
                self._reply(404, {"error": "autoscaler not enabled"})
                return
            self._reply(200, ctl.status())
            return
        if self.path.rstrip("/") == "/v1/slo":
            # the live ``slo`` block (same builder as the bench pin);
            # flush a sample first so the timeline includes traffic
            # served since the last 0.2s/5s tick — the fleet bench
            # reads this at phase close from every coordinator
            from ..obs.slo import SLO, slo_block
            from ..obs.timeseries import TIMESERIES
            TIMESERIES.sample()
            self._reply(200, slo_block(TIMESERIES, SLO))
            return
        if self.path.split("?")[0].rstrip("/") == "/v1/metrics/history":
            # windowed range reads over the time-series store
            # (obs/timeseries.py) — same unauthenticated node-internal
            # plane as the scrape endpoint below; federated worker
            # series are readable here too
            from ..obs.timeseries import TIMESERIES
            qs = self.path.split("?", 1)[1] if "?" in self.path else ""
            code, doc = TIMESERIES.history_doc(qs)
            self._reply(code, doc)
            return
        if self.path.split("?")[0].rstrip("/") == "/v1/metrics":
            # Prometheus scrape surface (unauthenticated, like
            # /v1/service — node-internal plane): the coordinator's
            # registry plus node-labeled series federated from worker
            # heartbeats (obs/exposition.py)
            from ..obs.exposition import render_exposition
            from ..obs.metrics import NODES, REGISTRY
            body = render_exposition(REGISTRY, nodes=NODES).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if not self._authenticate():
            return
        if self.path.rstrip("/") == "/v1/resourceGroup":
            self._reply(200, {"groups": self._srv.resource_groups.info()})
            return
        if self.path.rstrip("/") == "/v1/query":
            # query list for the UI (reference server/QueryResource.java)
            out = []
            for e in list(self._srv.runner.query_log)[-200:][::-1]:
                out.append({"queryId": e.query_id, "state": e.state,
                            "query": e.query,
                            "elapsedMs": round(e.elapsed_ms, 1)})
            self._reply(200, out)
            return
        if self.path.startswith("/v1/query/"):
            # live per-query detail: per-node wall/batches + split
            # timeline, updated WHILE the query runs (reference
            # server/QueryResource.java + webapp timeline page)
            qid = self.path[len("/v1/query/"):].strip("/")
            entry = next((e for e in self._srv.runner.query_log
                          if e.query_id == qid), None)
            if entry is None:
                self._reply(404, {"error": f"unknown query {qid!r}"})
                return
            stats = self._srv.runner.live_stats.get(qid)
            doc = {"queryId": entry.query_id, "state": entry.state,
                   "query": entry.query,
                   "elapsedMs": round(entry.elapsed_ms, 1),
                   "nodes": stats.snapshot() if stats is not None else [],
                   "splits": list(stats.splits) if stats is not None
                   else []}
            self._reply(200, doc)
            return
        if self.path.rstrip("/") in ("/ui", ""):
            body = _UI_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        m = self._match_executing()
        if m is None:
            self._reply(404, {"error": "not found"})
            return
        q, token = m
        try:
            page = q.next_page(token)
        except KeyError as e:
            self._reply(410, {"error": str(e)})
            return
        self._reply(200, self._results_doc(q, token, page=page),
                    self._session_headers(q))

    def do_PUT(self) -> None:
        # lifecycle changes need the same credentials as statements: an
        # unauthenticated peer must not be able to drain the server
        if not self._authenticate():
            return
        parts = self.path.strip("/").split("/")
        if parts == ["v1", "info", "state"]:
            n = int(self.headers.get("Content-Length", 0))
            state = json.loads(self.rfile.read(n) or b'""')
            if state == "SHUTTING_DOWN":
                self._srv.begin_shutdown()
                self._reply(200, {"state": "SHUTTING_DOWN"})
            else:
                self._reply(400, {"error": f"bad state {state!r}"})
            return
        self._reply(404, {"error": "not found"})

    def do_DELETE(self) -> None:
        if not self._authenticate():
            return
        m = self._match_executing()
        if m is None:
            self._reply(404, {"error": "not found"})
            return
        q, _ = m
        q.cancel()
        self._reply(200, {})

    def _authenticate(self) -> bool:
        """HTTP Basic against the installed password authenticator and
        Bearer (JWT) against the installed token authenticator
        (reference server/security/AuthenticationFilter.java chaining
        multiple authenticators); none installed = open server,
        header-asserted identity."""
        auth = self._srv.authenticator
        jwt = getattr(self._srv, "jwt_authenticator", None)
        if auth is None and jwt is None:
            return True
        import base64
        header = self.headers.get("Authorization", "")
        if jwt is not None and header.startswith("Bearer "):
            principal = jwt.authenticate(header[7:].strip())
            if principal:
                self._auth_user = principal
                return True
        if auth is not None and header.startswith("Basic "):
            try:
                raw = base64.b64decode(header[6:]).decode()
                user, _, password = raw.partition(":")
            except Exception:
                user, password = "", ""
            if auth.authenticate(user, password):
                self._auth_user = user
                return True
        body = json.dumps({"error": "Unauthorized"}).encode()
        self.send_response(401)
        self.send_header("WWW-Authenticate",
                         'Basic realm="presto-tpu"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return False

    def _match_executing(self):
        parts = self.path.strip("/").split("/")
        # v1/statement/executing/{id}/{slug}/{token}
        if len(parts) != 6 or parts[:3] != ["v1", "statement", "executing"]:
            return None
        q = self._srv.queries.get(parts[3])
        if q is None or q.slug != parts[4]:
            return None
        return q, int(parts[5])

    def _session_headers(self, q: _Query) -> Dict[str, str]:
        headers: Dict[str, str] = {}
        for k, v in q.set_session.items():
            headers["X-Presto-Set-Session"] = f"{k}={v}"
        for k in q.clear_session:
            headers["X-Presto-Clear-Session"] = k
        return headers

    def _results_doc(self, q: _Query, token: int, first: bool = False,
                     page=None) -> Dict:
        base = f"http://{self.headers.get('Host', 'localhost')}"
        doc: Dict = {
            "id": q.id,
            "infoUri": f"{base}/ui/query/{q.id}",
            "stats": {"state": q.state},
        }
        if first:
            doc["nextUri"] = (f"{base}/v1/statement/executing/"
                              f"{q.id}/{q.slug}/0")
            return doc
        if q.columns is not None:
            doc["columns"] = q.columns
        if page is not None:
            doc["data"] = page
            doc["nextUri"] = (f"{base}/v1/statement/executing/"
                              f"{q.id}/{q.slug}/{token + 1}")
        elif q.error is not None:
            doc["error"] = q.error
        return doc


class PrestoTpuServer:
    """Embeddable statement server over a LocalRunner."""

    def __init__(self, runner=None, host: str = "127.0.0.1", port: int = 0,
                 resource_groups: Optional[Dict] = None,
                 authenticator=None, jwt_authenticator=None,
                 discovery=None):
        from .resource_groups import ResourceGroupManager
        self.authenticator = authenticator
        self.jwt_authenticator = jwt_authenticator
        if runner is None:
            from ..exec.runner import LocalRunner
            runner = LocalRunner()
        self.runner = runner
        self._accepts_serving = _runner_accepts_serving(runner)
        self.queries: Dict[str, _Query] = {}
        self.shutting_down = False
        self._seq = 0
        self._lock = checked_lock("protocol.server")
        # admission: the default config keeps one query running at a
        # time (the single shared device); pass a rootGroups/selectors
        # dict for real concurrency tiers
        self.resource_groups = ResourceGroupManager(resource_groups)
        from ..exec.discovery import DiscoveryNodeManager
        # a fleet coordinator passes its ClusterRunner's discovery so
        # /v1/announce feeds the SAME membership the scheduler reads
        # (one shared worker pool across the fleet)
        self.discovery = (discovery if discovery is not None
                          else DiscoveryNodeManager())
        #: fleet membership (serving/fleet.FleetMember) — None until
        #: :meth:`enable_fleet`; a standalone coordinator never pays a
        #: fleet branch
        self.fleet = None
        #: elasticity control loop (exec/autoscale.AutoscaleController)
        #: — None unless wired by :func:`config.server_from_etc`
        #: (autoscale.enabled=true) or attached by the embedding
        #: harness; surfaced read-only at GET /v1/autoscale
        self.autoscaler = None
        #: statements whose LAST run drained within the single-round-
        #: trip grace: the inline-lane gate (do_POST). Keyed by raw
        #: statement text; a slow re-run (e.g. after a cache
        #: invalidation) evicts itself, so a statement can only hold a
        #: handler thread for one slow execution before reverting to
        #: the producer pool. Bounded so adversarial unique statements
        #: can't grow it. SERVING_INLINE_LANE=0 disables the lane.
        self._fast_sql: Dict[str, bool] = {}
        self._inline_lane = os.environ.get(
            "SERVING_INLINE_LANE", "1") != "0"
        self._qid_date: Optional[datetime.date] = None
        self._qid_prefix = ""

        class _StatementHTTPServer(ThreadingHTTPServer):
            # a 100-client fleet opening a connection per statement
            # overflows socketserver's default listen backlog of FIVE:
            # dropped SYNs retransmit on the kernel's 1s/3s timers and
            # every affected query's latency quantizes to whole
            # seconds. Found load-testing SERVING_r02; sized well past
            # any bench fleet.
            request_queue_size = 1024

            # live client sockets, tracked so kill() can reset them:
            # shutting the listener only stops NEW connections — a
            # "dead" in-process coordinator would otherwise keep
            # serving its established keep-alives forever, and a chaos
            # kill would never exercise client failover
            def get_request(self):
                sock, addr = super().get_request()
                with self._socks_lock:
                    self._client_socks.add(sock)
                return sock, addr

            def shutdown_request(self, request):
                with self._socks_lock:
                    self._client_socks.discard(request)
                super().shutdown_request(request)

            def close_client_connections(self):
                import socket as _socket
                with self._socks_lock:
                    socks = list(self._client_socks)
                    self._client_socks.clear()
                for s in socks:
                    try:
                        s.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass

        self.httpd = _StatementHTTPServer((host, port), _Handler)
        self.httpd._client_socks = set()  # type: ignore[attr-defined]
        self.httpd._socks_lock = threading.Lock()  # type: ignore[attr-defined]
        self.httpd.presto = self      # type: ignore[attr-defined]
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)

    def create_query(self, sql: str, overrides: Dict[str, str],
                     user: str = "", source: str = "",
                     inline: bool = False) -> _Query:
        today = datetime.date.today()
        with self._lock:
            self._seq += 1
            if self._qid_date != today:
                # strftime costs ~8µs; at serving rates that's real
                # money for a string that changes once a day
                self._qid_date = today
                self._qid_prefix = today.strftime("%Y%m%d")
            qid = f"{self._qid_prefix}_{self._seq:06d}"
        admission = self.resource_groups.submit(user=user, source=source)
        try:
            q = _Query(qid, secrets.token_hex(8), sql, self.runner,
                       overrides, admission, user=user,
                       accepts_serving=self._accepts_serving,
                       inline=inline)
        except BaseException:
            # a construction failure must not strand the queue slot
            admission.release()
            raise
        with self._lock:
            self.queries[qid] = q
            if len(self.queries) > 200:   # evict oldest drained queries
                for old_id in list(self.queries):
                    old = self.queries[old_id]
                    if old is not q and old.state in ("FINISHED", "FAILED"):
                        del self.queries[old_id]
                    if len(self.queries) <= 100:
                        break
        return q

    def note_fast_statement(self, sql: str, fast: bool) -> None:
        """Inline-lane memo maintenance, called from the statement POST
        at reply time: a single-round-trip drain earns the statement an
        inline seat; a slow or multi-page run revokes it."""
        with self._lock:
            if not fast:
                self._fast_sql.pop(sql, None)
                return
            if sql not in self._fast_sql and len(self._fast_sql) >= 512:
                self._fast_sql.pop(next(iter(self._fast_sql)))
            self._fast_sql[sql] = True

    def enable_fleet(self, node_id: str, peers=(),
                     advertised_host: str = "127.0.0.1",
                     heartbeat_s: float = 1.0,
                     staleness_grace_s: Optional[float] = None):
        """Join a coordinator fleet (serving/fleet.py): coherent caches
        via write-bump broadcast, fleet-wide resource-group limits via
        heartbeat federation. ``peers`` is the other coordinators'
        base URLs; call :meth:`start` (or have a bound port) first so
        the advertised self URL is real. Idempotent per server."""
        if self.fleet is not None:
            return self.fleet
        from ..serving.fleet import FleetMember
        catalogs = getattr(
            getattr(self.runner, "session", None), "catalogs", None)
        self.fleet = FleetMember(
            node_id, f"http://{advertised_host}:{self.port}",
            catalogs=catalogs,
            resource_groups=self.resource_groups,
            discovery=self.discovery, peers=peers,
            heartbeat_s=heartbeat_s,
            staleness_grace_s=staleness_grace_s)
        self.fleet.start()
        return self.fleet

    def start(self) -> None:
        # the health plane rides server lifetime: one process-wide
        # sampler feeds the time-series store, the SLO tracker
        # evaluates after every tick (both idempotent — a process
        # running several servers shares one plane)
        from ..obs.slo import SLO
        from ..obs.timeseries import TIMESERIES
        SLO.install()
        TIMESERIES.ensure_started()
        self._thread.start()

    def begin_shutdown(self) -> None:
        """Drain: refuse new statements (503), let running queries page
        out, then stop the server (the coordinator half of the worker's
        GracefulShutdownHandler-style drain)."""
        self.shutting_down = True
        if self.fleet is not None:
            # clean drain: tell peers to drop our federated counts NOW
            # (a drain is not a loss — no staleness grace, no
            # coordinator_lost_total)
            self.fleet.leave()

        def drain():
            # terminal state is set when the last page is ENQUEUED, not
            # when the client fetched it: wait for page queues to empty
            # too, under a grace window so an abandoned client cannot
            # pin the drain forever
            grace_until = None
            while True:
                qs = list(self.queries.values())
                if any(q.state in ("QUEUED", "RUNNING") for q in qs):
                    grace_until = None
                elif not any(not q._pages.empty() for q in qs):
                    break
                else:
                    now = time.monotonic()
                    if grace_until is None:
                        grace_until = now + 30.0
                    elif now > grace_until:
                        break
                time.sleep(0.2)
            self.stop()
        threading.Thread(target=drain, daemon=True).start()

    def stop(self) -> None:
        # shutdown() handshakes with serve_forever — calling it on a
        # server whose loop never started (embedded create_query use)
        # would block forever
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.fleet is not None:
            self.fleet.stop()
        if self._thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()

    def kill(self) -> None:
        """Process-death stand-in for in-process chaos tests: stop
        accepting, RESET every established client connection (a real
        SIGKILL'd process drops its sockets — in-flight requests see a
        transport error, exercising client failover), and silence the
        fleet heartbeat so peers declare this coordinator lost via the
        staleness grace. No drain, no ``leaving`` farewell."""
        if self.fleet is not None:
            self.fleet.stop()
        self.shutting_down = True
        if self._thread.is_alive():
            self.httpd.shutdown()
        self.httpd.close_client_connections()  # type: ignore[attr-defined]
        self.httpd.server_close()


#: the protocol-facing name (reference dispatcher/QueuedStatementResource
#: serves POST /v1/statement); PrestoTpuServer remains the historical alias
StatementServer = PrestoTpuServer

"""Hierarchical resource groups: admission control for the statement server.

The role of the reference's resource-group subsystem (reference
presto-main/.../execution/resourcegroups/InternalResourceGroup.java —
hierarchical concurrency/queue limits, weighted-fair dequeue across
subgroups via WeightedFairQueue.java;
InternalResourceGroupManager.java + the file-based configuration of
presto-resource-group-managers). Queries run in LEAF groups; a query is
eligible to start only while every group on its path is under its own
``hard_concurrency_limit``; full queues reject new work
(QUERY_QUEUE_FULL).

Configuration mirrors the file manager's JSON shape::

    {"rootGroups": [
        {"name": "global", "hardConcurrencyLimit": 4, "maxQueued": 100,
         "subGroups": [
            {"name": "adhoc", "hardConcurrencyLimit": 2,
             "schedulingWeight": 1},
            {"name": "etl", "hardConcurrencyLimit": 3,
             "schedulingWeight": 3}]}],
     "selectors": [
        {"user": "etl-.*", "group": "global.etl"},
        {"group": "global.adhoc"}]}

Dequeue is deterministic weighted-fair: among sibling subgroups with
queued queries, the one with the lowest running/weight ratio goes first.

Serving-plane extensions (presto_tpu/serving/):

- ``softMemoryLimit`` / ``hardMemoryLimit`` (bytes): running queries
  charge their device-memory reservations to the admitting group chain
  (serving/groups.QueryServingContext); a group over its soft limit
  queues new queries instead of starting them, a reservation past the
  hard limit kills the requesting query (reference
  InternalResourceGroup.softMemoryLimit semantics).
- ``queryQueuedTimeout`` (duration): a query still queued past the
  deadline fails with QUERY_QUEUED_TIMEOUT instead of waiting forever
  (overridable per query via the ``query_queued_timeout`` session
  property).
- ``schedulingWeight`` additionally drives the device scheduler's
  per-group stride shares (exec/taskexec.py), so the weight governs
  device quanta, not just dequeue order.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional

from ..obs.metrics import REGISTRY

_ADMITTED = REGISTRY.counter("resource_group_admitted_total")
_QUEUED = REGISTRY.counter("resource_group_queued_total")
_REJECTED = REGISTRY.counter("resource_group_rejected_total")
_QUEUE_TIMEOUTS = REGISTRY.counter("resource_group_queued_timeout_total")


class QueryQueueFullError(RuntimeError):
    pass


class QueryQueuedTimeoutError(RuntimeError):
    """Admission deadline exceeded (``queryQueuedTimeout`` group config
    or ``query_queued_timeout`` session property)."""

    name = "QUERY_QUEUED_TIMEOUT"


def _parse_limit_bytes(v) -> Optional[int]:
    if v is None:
        return None
    return int(v)


def _parse_timeout_s(v) -> Optional[float]:
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    from ..exec.cluster import parse_duration_s
    return parse_duration_s(v)


def _parse_slo(spec) -> Optional[dict]:
    """Normalize and validate a per-group ``slo`` block (see
    docs/serving.md): latency objective (``latencyTargetMs`` +
    ``latencyObjective``), availability objective
    (``availabilityObjective``), optional ``windows`` (seconds).
    Objectives are fractions in (0, 1); fail fast on malformed config
    so a typo'd SLO cannot silently track nothing."""
    if spec is None:
        return None
    if not isinstance(spec, dict):
        raise ValueError(f"slo block must be an object, got {spec!r}")
    out: dict = {}
    lat_obj = spec.get("latencyObjective")
    if lat_obj is not None:
        target_ms = spec.get("latencyTargetMs")
        if target_ms is None:
            raise ValueError("slo.latencyObjective requires "
                             "slo.latencyTargetMs")
        out["latencyObjective"] = float(lat_obj)
        out["latencyTargetMs"] = float(target_ms)
    avail = spec.get("availabilityObjective")
    if avail is not None:
        out["availabilityObjective"] = float(avail)
    for key in ("latencyObjective", "availabilityObjective"):
        v = out.get(key)
        if v is not None and not 0.0 < v < 1.0:
            raise ValueError(f"slo.{key} must be in (0, 1), got {v}")
    if not out:
        raise ValueError("slo block declares no objective "
                         "(latencyObjective or availabilityObjective)")
    windows = spec.get("windows")
    if windows is not None:
        ws = sorted(float(w) for w in windows)
        if not ws or any(w <= 0 for w in ws):
            raise ValueError(f"slo.windows must be positive seconds, "
                             f"got {windows!r}")
        out["windows"] = ws
    return out


class Admission:
    """Handle for one submitted query: wait() blocks until a run slot is
    granted; release() frees it (must be called exactly once)."""

    def __init__(self, group: "ResourceGroup"):
        self.group = group
        self.submit_time = time.monotonic()
        self._granted = threading.Event()
        self._released = False

    def queued_timeout_s(self, override=None) -> Optional[float]:
        """Effective admission deadline in seconds: the per-query
        session-property override wins, else the leaf group's
        ``queryQueuedTimeout``; None = wait forever."""
        if override is not None:
            return _parse_timeout_s(override)
        return self.group.query_queued_timeout

    def time_out(self) -> None:
        """Mark this admission as dead-on-queue: releases the queue slot
        and counts the timeout (callers raise QueryQueuedTimeoutError)."""
        _QUEUE_TIMEOUTS.inc()
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._granted.wait(timeout)

    @property
    def granted(self) -> bool:
        return self._granted.is_set()

    def release(self) -> None:
        with self.group.manager.lock:
            if self._released:
                return
            self._released = True
            if self.granted:
                g = self.group
                while g is not None:
                    g.running -= 1
                    g = g.parent
            else:
                # abandoned while QUEUED (cancel before grant): leave no
                # ghost entry for the dispatcher to grant a slot to
                try:
                    self.group.queue.remove(self)
                except ValueError:
                    pass
        self.group.manager._dispatch()


class ResourceGroup:
    def __init__(self, manager: "ResourceGroupManager", name: str,
                 parent: Optional["ResourceGroup"],
                 hard_concurrency_limit: int = 1,
                 max_queued: int = 100, scheduling_weight: int = 1,
                 soft_memory_limit: Optional[int] = None,
                 hard_memory_limit: Optional[int] = None,
                 query_queued_timeout: Optional[float] = None,
                 slo: Optional[dict] = None):
        self.manager = manager
        self.name = name
        self.parent = parent
        self.path = name if parent is None else f"{parent.path}.{name}"
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.scheduling_weight = max(scheduling_weight, 1)
        #: device-memory bytes charged by this group's running queries
        #: (and its descendants'), maintained under manager.memory_lock
        #: by serving.groups.QueryServingContext
        self.soft_memory_limit = soft_memory_limit
        self.hard_memory_limit = hard_memory_limit
        self.memory_reserved = 0
        self.query_queued_timeout = query_queued_timeout
        #: normalized SLO block (``_parse_slo``) — consumed by
        #: obs/slo.py through ``info()``; None = no objectives
        self.slo = slo
        self.children: Dict[str, ResourceGroup] = {}
        self.queue: List[Admission] = []
        self.running = 0

    # -- accounting (manager.lock held) --------------------------------------
    def queued_total(self) -> int:
        return len(self.queue) + sum(c.queued_total()
                                     for c in self.children.values())

    def _remote_running(self) -> int:
        """Running count this group's path holds on OTHER coordinators
        (fleet federation; serving/fleet.py). 0 when standalone. Called
        under manager.lock — the provider takes only its own lock
        (order: resourcegroups.manager -> fleet.member)."""
        fed = self.manager.federation
        if fed is None:
            return 0
        try:
            return int(fed.remote_running(self.path))
        except Exception:
            return 0

    def _remote_memory(self) -> int:
        fed = self.manager.federation
        if fed is None:
            return 0
        try:
            return int(fed.remote_memory(self.path))
        except Exception:
            return 0

    def over_soft_memory(self) -> bool:
        return (self.soft_memory_limit is not None
                and self.memory_reserved + self._remote_memory()
                > self.soft_memory_limit)

    def _over_soft_memory_local(self) -> bool:
        return (self.soft_memory_limit is not None
                and self.memory_reserved > self.soft_memory_limit)

    def can_run_more(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            remote = g._remote_running()
            if g.running + remote >= g.hard_concurrency_limit:
                if remote and g.running < g.hard_concurrency_limit:
                    # a coordinator-local view would have admitted:
                    # the fleet-wide limit is what blocked
                    g.manager._note_remote_blocked()
                return False
            if g.over_soft_memory():
                # kill-or-queue: over the soft limit the group keeps its
                # running queries but admits nothing new until memory
                # returns (reference InternalResourceGroup.canRunMore)
                if not g._over_soft_memory_local():
                    g.manager._note_remote_blocked()
                return False
            g = g.parent
        return True

    def _pick_queued(self) -> Optional["ResourceGroup"]:
        """Deepest-first weighted-fair choice of a descendant leaf-queue
        with work, honoring every level's concurrency limit (federated:
        a group whose fleet-wide running count fills its limit is not a
        candidate, so it cannot shadow an admissible sibling)."""
        if self.running + self._remote_running() \
                >= self.hard_concurrency_limit \
                or self.over_soft_memory():
            return None
        candidates = [c._pick_queued() for c in self.children.values()]
        candidates = [c for c in candidates if c is not None]
        if self.queue:
            candidates.append(self)
        if not candidates:
            return None
        return min(candidates,
                   key=lambda g: (g.running / g.scheduling_weight,
                                  g.path))

    def info(self) -> dict:
        if self.over_soft_memory():
            state = "OVER_SOFT_MEMORY_LIMIT"
        elif self.running >= self.hard_concurrency_limit:
            state = "FULL"
        else:
            state = "CAN_RUN"
        return {
            "id": self.path,
            "state": state,
            "hardConcurrencyLimit": self.hard_concurrency_limit,
            "maxQueued": self.max_queued,
            "schedulingWeight": self.scheduling_weight,
            "softMemoryLimitBytes": self.soft_memory_limit,
            "hardMemoryLimitBytes": self.hard_memory_limit,
            "memoryReservedBytes": self.memory_reserved,
            "queryQueuedTimeoutS": self.query_queued_timeout,
            "slo": self.slo,
            "numRunning": self.running,
            "numQueued": len(self.queue),
            "subGroups": [c.info() for c in self.children.values()],
        }


_SCOPE_SEQ = iter(range(1, 1 << 62))


class ResourceGroupManager:
    def __init__(self, config: Optional[dict] = None):
        from .._devtools.lockcheck import checked_lock
        #: process-unique scope for this manager's groups: same-named
        #: groups of DIFFERENT managers (two embedded servers in one
        #: process) must not share one device-scheduler stride account
        self.scope = f"rg{next(_SCOPE_SEQ)}"
        self.lock = checked_lock("resourcegroups.manager")
        #: guards the per-group memory ledgers — separate from ``lock``
        #: because memory charges arrive from inside QueryMemoryPool
        #: reservations (hot path) while ``lock`` serializes dispatch
        self.memory_lock = checked_lock("resourcegroups.memory")
        #: fleet federation provider (serving/fleet.FleetMember), set by
        #: the member on install; None = standalone coordinator. Must
        #: expose remote_running(path) / remote_memory(path) /
        #: note_remote_blocked(), and must never call back into this
        #: manager while holding its own lock (lock order:
        #: resourcegroups.manager -> fleet.member).
        self.federation = None
        self.roots: Dict[str, ResourceGroup] = {}
        self.selectors: List[dict] = []
        config = config or {
            "rootGroups": [{"name": "global", "hardConcurrencyLimit": 1,
                            "maxQueued": 200}],
            "selectors": [{"group": "global"}],
        }
        for spec in config.get("rootGroups", []):
            self._build(spec, None)
        self.selectors = list(config.get("selectors", []))
        # the system.runtime.resource_groups table reflects every live
        # manager in the process (weak registration)
        from ..serving.groups import register_manager
        register_manager(self)

    def _build(self, spec: dict, parent: Optional[ResourceGroup]) -> None:
        g = ResourceGroup(
            self, spec["name"], parent,
            hard_concurrency_limit=int(
                spec.get("hardConcurrencyLimit", 1)),
            max_queued=int(spec.get("maxQueued", 100)),
            scheduling_weight=int(spec.get("schedulingWeight", 1)),
            soft_memory_limit=_parse_limit_bytes(
                spec.get("softMemoryLimit")),
            hard_memory_limit=_parse_limit_bytes(
                spec.get("hardMemoryLimit")),
            query_queued_timeout=_parse_timeout_s(
                spec.get("queryQueuedTimeout")),
            slo=_parse_slo(spec.get("slo")))
        if parent is None:
            self.roots[g.name] = g
        else:
            parent.children[g.name] = g
        for sub in spec.get("subGroups", []):
            self._build(sub, g)

    # -- selection -----------------------------------------------------------
    def _group_for(self, user: str, source: str) -> ResourceGroup:
        for sel in self.selectors:
            if "user" in sel and not re.fullmatch(sel["user"], user or ""):
                continue
            if "source" in sel and not re.fullmatch(sel["source"],
                                                    source or ""):
                continue
            return self._resolve(sel["group"])
        # no selector matched: first root
        return next(iter(self.roots.values()))

    def _resolve(self, path: str) -> ResourceGroup:
        parts = path.split(".")
        g = self.roots[parts[0]]
        for p in parts[1:]:
            g = g.children[p]
        return g

    # -- submission ----------------------------------------------------------
    def submit(self, user: str = "", source: str = "") -> Admission:
        with self.lock:
            group = self._group_for(user, source)
            if group.queued_total() >= group.max_queued:
                _REJECTED.inc()
                raise QueryQueueFullError(
                    f"Too many queued queries for {group.path!r}")
            adm = Admission(group)
            group.queue.append(adm)
            _QUEUED.inc()
        self._dispatch()
        return adm

    def _dispatch(self) -> None:
        with self.lock:
            while True:
                started = False
                for root in self.roots.values():
                    g = root._pick_queued()
                    if g is None or not g.queue:
                        continue
                    if not g.can_run_more():
                        continue
                    adm = g.queue.pop(0)
                    walk: Optional[ResourceGroup] = g
                    while walk is not None:
                        walk.running += 1
                        walk = walk.parent
                    adm._granted.set()
                    _ADMITTED.inc()
                    started = True
                if not started:
                    return

    def _note_remote_blocked(self) -> None:
        fed = self.federation
        if fed is not None:
            try:
                fed.note_remote_blocked()
            except Exception:
                pass

    def group_counts(self) -> Dict[str, dict]:
        """Per-group ``{running, queued, memory}`` snapshot, keyed by
        dotted path — the fleet heartbeat payload (serving/fleet.py)."""
        out: Dict[str, dict] = {}
        with self.lock:
            stack = list(self.roots.values())
            while stack:
                g = stack.pop()
                out[g.path] = {"running": g.running,
                               "queued": len(g.queue),
                               "memory": g.memory_reserved}
                stack.extend(g.children.values())
        return out

    def info(self) -> List[dict]:
        with self.lock:
            return [g.info() for g in self.roots.values()]

"""Hierarchical resource groups: admission control for the statement server.

The role of the reference's resource-group subsystem (reference
presto-main/.../execution/resourcegroups/InternalResourceGroup.java —
hierarchical concurrency/queue limits, weighted-fair dequeue across
subgroups via WeightedFairQueue.java;
InternalResourceGroupManager.java + the file-based configuration of
presto-resource-group-managers). Queries run in LEAF groups; a query is
eligible to start only while every group on its path is under its own
``hard_concurrency_limit``; full queues reject new work
(QUERY_QUEUE_FULL).

Configuration mirrors the file manager's JSON shape::

    {"rootGroups": [
        {"name": "global", "hardConcurrencyLimit": 4, "maxQueued": 100,
         "subGroups": [
            {"name": "adhoc", "hardConcurrencyLimit": 2,
             "schedulingWeight": 1},
            {"name": "etl", "hardConcurrencyLimit": 3,
             "schedulingWeight": 3}]}],
     "selectors": [
        {"user": "etl-.*", "group": "global.etl"},
        {"group": "global.adhoc"}]}

Dequeue is deterministic weighted-fair: among sibling subgroups with
queued queries, the one with the lowest running/weight ratio goes first.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    pass


class Admission:
    """Handle for one submitted query: wait() blocks until a run slot is
    granted; release() frees it (must be called exactly once)."""

    def __init__(self, group: "ResourceGroup"):
        self.group = group
        self._granted = threading.Event()
        self._released = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._granted.wait(timeout)

    @property
    def granted(self) -> bool:
        return self._granted.is_set()

    def release(self) -> None:
        with self.group.manager.lock:
            if self._released:
                return
            self._released = True
            if self.granted:
                g = self.group
                while g is not None:
                    g.running -= 1
                    g = g.parent
            else:
                # abandoned while QUEUED (cancel before grant): leave no
                # ghost entry for the dispatcher to grant a slot to
                try:
                    self.group.queue.remove(self)
                except ValueError:
                    pass
        self.group.manager._dispatch()


class ResourceGroup:
    def __init__(self, manager: "ResourceGroupManager", name: str,
                 parent: Optional["ResourceGroup"],
                 hard_concurrency_limit: int = 1,
                 max_queued: int = 100, scheduling_weight: int = 1):
        self.manager = manager
        self.name = name
        self.parent = parent
        self.path = name if parent is None else f"{parent.path}.{name}"
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.scheduling_weight = max(scheduling_weight, 1)
        self.children: Dict[str, ResourceGroup] = {}
        self.queue: List[Admission] = []
        self.running = 0

    # -- accounting (manager.lock held) --------------------------------------
    def queued_total(self) -> int:
        return len(self.queue) + sum(c.queued_total()
                                     for c in self.children.values())

    def can_run_more(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency_limit:
                return False
            g = g.parent
        return True

    def _pick_queued(self) -> Optional["ResourceGroup"]:
        """Deepest-first weighted-fair choice of a descendant leaf-queue
        with work, honoring every level's concurrency limit."""
        if self.running >= self.hard_concurrency_limit:
            return None
        candidates = [c._pick_queued() for c in self.children.values()]
        candidates = [c for c in candidates if c is not None]
        if self.queue:
            candidates.append(self)
        if not candidates:
            return None
        return min(candidates,
                   key=lambda g: (g.running / g.scheduling_weight,
                                  g.path))

    def info(self) -> dict:
        return {
            "id": self.path,
            "hardConcurrencyLimit": self.hard_concurrency_limit,
            "maxQueued": self.max_queued,
            "schedulingWeight": self.scheduling_weight,
            "numRunning": self.running,
            "numQueued": len(self.queue),
            "subGroups": [c.info() for c in self.children.values()],
        }


class ResourceGroupManager:
    def __init__(self, config: Optional[dict] = None):
        self.lock = threading.Lock()
        self.roots: Dict[str, ResourceGroup] = {}
        self.selectors: List[dict] = []
        config = config or {
            "rootGroups": [{"name": "global", "hardConcurrencyLimit": 1,
                            "maxQueued": 200}],
            "selectors": [{"group": "global"}],
        }
        for spec in config.get("rootGroups", []):
            self._build(spec, None)
        self.selectors = list(config.get("selectors", []))

    def _build(self, spec: dict, parent: Optional[ResourceGroup]) -> None:
        g = ResourceGroup(
            self, spec["name"], parent,
            hard_concurrency_limit=int(
                spec.get("hardConcurrencyLimit", 1)),
            max_queued=int(spec.get("maxQueued", 100)),
            scheduling_weight=int(spec.get("schedulingWeight", 1)))
        if parent is None:
            self.roots[g.name] = g
        else:
            parent.children[g.name] = g
        for sub in spec.get("subGroups", []):
            self._build(sub, g)

    # -- selection -----------------------------------------------------------
    def _group_for(self, user: str, source: str) -> ResourceGroup:
        for sel in self.selectors:
            if "user" in sel and not re.fullmatch(sel["user"], user or ""):
                continue
            if "source" in sel and not re.fullmatch(sel["source"],
                                                    source or ""):
                continue
            return self._resolve(sel["group"])
        # no selector matched: first root
        return next(iter(self.roots.values()))

    def _resolve(self, path: str) -> ResourceGroup:
        parts = path.split(".")
        g = self.roots[parts[0]]
        for p in parts[1:]:
            g = g.children[p]
        return g

    # -- submission ----------------------------------------------------------
    def submit(self, user: str = "", source: str = "") -> Admission:
        with self.lock:
            group = self._group_for(user, source)
            if group.queued_total() >= group.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for {group.path!r}")
            adm = Admission(group)
            group.queue.append(adm)
        self._dispatch()
        return adm

    def _dispatch(self) -> None:
        with self.lock:
            while True:
                started = False
                for root in self.roots.values():
                    g = root._pick_queued()
                    if g is None or not g.queue:
                        continue
                    if not g.can_run_more():
                        continue
                    adm = g.queue.pop(0)
                    walk: Optional[ResourceGroup] = g
                    while walk is not None:
                        walk.running += 1
                        walk = walk.parent
                    adm._granted.set()
                    started = True
                if not started:
                    return

    def info(self) -> List[dict]:
        with self.lock:
            return [g.info() for g in self.roots.values()]

"""Security: password authentication + catalog access control.

The roles of the reference's security surface reduced to its two
load-bearing pieces (reference server/security/AuthenticationFilter.java
+ PasswordAuthenticatorManager with the file-based authenticator of
presto-password-authenticators/.../file/FileAuthenticator.java, and
security/AccessControlManager.java + spi/security/SystemAccessControl
with the catalog rules of the file-based access controller):

- ``PasswordAuthenticator``: user -> password map (or a ``user:password``
  lines file); the statement server challenges with HTTP Basic when one
  is installed.
- ``AccessControl``: catalog-level allow/deny rules evaluated per user,
  same shape as the reference's file-based catalog rules::

      {"catalogs": [
          {"user": "admin", "catalog": ".*", "allow": true},
          {"catalog": "system", "allow": false},
          {"allow": true}]}

  First matching rule wins (user/catalog are full-match regexes,
  both optional).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional


class AccessDeniedError(PermissionError):
    pass


class PasswordAuthenticator:
    def __init__(self, users: Optional[Dict[str, str]] = None,
                 path: Optional[str] = None):
        self.users = dict(users or {})
        if path:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line and ":" in line and not line.startswith("#"):
                        u, p = line.split(":", 1)
                        self.users[u] = p

    def authenticate(self, user: str, password: str) -> bool:
        import hmac
        expected = self.users.get(user)
        return expected is not None and hmac.compare_digest(
            expected, password)


class JwtAuthenticator:
    """Bearer-token (JWT HS256) authentication: the TPU-native stand-in
    for the reference's JsonWebTokenAuthenticator (reference
    server/security/jwt — signature verification + exp check, principal
    from the ``sub`` claim). Stdlib-only: HMAC-SHA256 over the signing
    input, base64url decoding, no external JOSE dependency."""

    def __init__(self, secret: str, required_audience: str = ""):
        self.secret = secret.encode("utf-8")
        self.audience = required_audience

    def authenticate(self, token: str):
        """Returns the principal (sub) or None when invalid/expired."""
        import base64
        import hashlib
        import hmac
        import json
        import time

        def b64d(s: str) -> bytes:
            return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            header = json.loads(b64d(header_b64))
            if header.get("alg") != "HS256":
                return None
            signing_input = f"{header_b64}.{payload_b64}".encode("ascii")
            expect = hmac.new(self.secret, signing_input,
                              hashlib.sha256).digest()
            if not hmac.compare_digest(expect, b64d(sig_b64)):
                return None
            claims = json.loads(b64d(payload_b64))
            if "exp" in claims and time.time() >= float(claims["exp"]):
                return None
            if self.audience:
                aud = claims.get("aud")
                auds = aud if isinstance(aud, list) else [aud]
                if self.audience not in auds:
                    return None
            return claims.get("sub")
        except Exception:
            return None

    @staticmethod
    def issue(secret: str, sub: str, exp: Optional[float] = None,
              aud: str = "") -> str:
        """Mint a token (tests / trusted internal callers)."""
        import base64
        import hashlib
        import hmac
        import json

        def b64e(b: bytes) -> str:
            return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

        claims: dict = {"sub": sub}
        if exp is not None:
            claims["exp"] = exp
        if aud:
            claims["aud"] = aud
        h = b64e(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        p = b64e(json.dumps(claims).encode())
        sig = hmac.new(secret.encode(), f"{h}.{p}".encode(),
                       hashlib.sha256).digest()
        return f"{h}.{p}.{b64e(sig)}"


class RoleManager:
    """Roles, role grants, and table privileges (reference
    spi/security/RoleGrant + GrantInfo, AccessControlManager grant
    paths; the SQL surface is CREATE/DROP ROLE, GRANT/REVOKE,
    SET ROLE, SHOW ROLES/GRANTS).

    Enforcement model: permissive until ``enforce`` is set (matching
    the engine's default-open access control); when enforcing, a user
    must hold a privilege directly or through a granted role, and the
    built-in ``admin`` role bypasses checks and gates role/grant
    management."""

    ADMIN = "admin"

    def __init__(self, enforce: bool = False):
        self.enforce = enforce
        self.roles: set = {self.ADMIN}
        self.user_roles: Dict[str, set] = {}
        # (grantee, catalog, table) -> set of privileges
        self.table_grants: Dict[tuple, set] = {}

    # -- management (admin-gated when enforcing) -----------------------------
    def _check_admin(self, user: str) -> None:
        if self.enforce and not self.is_admin(user):
            raise AccessDeniedError(
                f"Access Denied: {user!r} is not in the admin role")

    def is_admin(self, user: str) -> bool:
        return self.ADMIN in self.user_roles.get(user, set())

    def create_role(self, name: str, user: str) -> None:
        self._check_admin(user)
        if name in self.roles:
            raise ValueError(f"role {name!r} already exists")
        self.roles.add(name)

    def drop_role(self, name: str, user: str) -> None:
        self._check_admin(user)
        if name == self.ADMIN:
            raise ValueError("cannot drop the admin role")
        self.roles.discard(name)
        for rs in self.user_roles.values():
            rs.discard(name)

    def grant_roles(self, roles, grantees, user: str) -> None:
        self._check_admin(user)
        for r in roles:
            if r not in self.roles:
                raise ValueError(f"role {r!r} does not exist")
            for g in grantees:
                self.user_roles.setdefault(g, set()).add(r)

    def revoke_roles(self, roles, grantees, user: str) -> None:
        self._check_admin(user)
        for g in grantees:
            for r in roles:
                self.user_roles.get(g, set()).discard(r)

    def grant_table(self, privileges, catalog: str, table: str,
                    grantee: str, user: str) -> None:
        self._check_admin(user)
        key = (grantee, catalog, table)
        self.table_grants.setdefault(key, set()).update(
            p.upper() for p in privileges)

    def revoke_table(self, privileges, catalog: str, table: str,
                     grantee: str, user: str) -> None:
        self._check_admin(user)
        key = (grantee, catalog, table)
        have = self.table_grants.get(key)
        if have:
            have.difference_update(p.upper() for p in privileges)

    # -- checks --------------------------------------------------------------
    def _grantees_of(self, user: str):
        return {user} | self.user_roles.get(user, set())

    def has_table_privilege(self, user: str, catalog: str, table: str,
                            privilege: str) -> bool:
        if not self.enforce or self.is_admin(user):
            return True
        p = privilege.upper()
        for g in self._grantees_of(user):
            if p in self.table_grants.get((g, catalog, table), set()):
                return True
        return False

    def check_table_privilege(self, user: str, catalog: str, table: str,
                              privilege: str) -> None:
        if not self.has_table_privilege(user, catalog, table, privilege):
            raise AccessDeniedError(
                f"Access Denied: user {user!r} lacks {privilege} on "
                f"{catalog}.{table}")

    # -- listings ------------------------------------------------------------
    def list_roles(self):
        return sorted(self.roles)

    def list_grants(self, table=None):
        out = []
        for (g, cat, tab), privs in sorted(self.table_grants.items()):
            if table is not None and (cat, tab) != table:
                continue
            for p in sorted(privs):
                out.append((g, cat, tab, p))
        return out


class AccessControl:
    """First-match catalog rules; default-deny when rules exist, the
    permissive allow-all when constructed with no rules."""

    def __init__(self, rules: Optional[dict] = None):
        self.catalog_rules: List[dict] = \
            list((rules or {}).get("catalogs", []))

    def can_access_catalog(self, user: str, catalog: str) -> bool:
        if not self.catalog_rules:
            return True
        for rule in self.catalog_rules:
            if "user" in rule and not re.fullmatch(rule["user"],
                                                   user or ""):
                continue
            if "catalog" in rule and not re.fullmatch(rule["catalog"],
                                                      catalog):
                continue
            return bool(rule.get("allow", True))
        return False

    def check_can_access_catalog(self, user: str, catalog: str) -> None:
        if not self.can_access_catalog(user, catalog):
            raise AccessDeniedError(
                f"Access Denied: user {user!r} cannot access catalog "
                f"{catalog!r}")

    def filter_catalogs(self, user: str, catalogs: List[str]) -> List[str]:
        return [c for c in catalogs if self.can_access_catalog(user, c)]


class SecuredCatalogs:
    """CatalogManager view that enforces access control on every
    resolution — the planner/executor path needs no security knowledge
    (the reference injects this the same way: MetadataManager resolves
    through AccessControl-checked connectors)."""

    def __init__(self, inner, user: str, access_control: AccessControl):
        self._inner = inner
        self._user = user
        self._ac = access_control

    def get(self, name: str):
        self._ac.check_can_access_catalog(self._user, name)
        return self._inner.get(name)

    def names(self) -> List[str]:
        return self._ac.filter_catalogs(self._user, self._inner.names())

    def register(self, name: str, connector) -> None:
        self._inner.register(name, connector)

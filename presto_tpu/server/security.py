"""Security: password authentication + catalog access control.

The roles of the reference's security surface reduced to its two
load-bearing pieces (reference server/security/AuthenticationFilter.java
+ PasswordAuthenticatorManager with the file-based authenticator of
presto-password-authenticators/.../file/FileAuthenticator.java, and
security/AccessControlManager.java + spi/security/SystemAccessControl
with the catalog rules of the file-based access controller):

- ``PasswordAuthenticator``: user -> password map (or a ``user:password``
  lines file); the statement server challenges with HTTP Basic when one
  is installed.
- ``AccessControl``: catalog-level allow/deny rules evaluated per user,
  same shape as the reference's file-based catalog rules::

      {"catalogs": [
          {"user": "admin", "catalog": ".*", "allow": true},
          {"catalog": "system", "allow": false},
          {"allow": true}]}

  First matching rule wins (user/catalog are full-match regexes,
  both optional).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional


class AccessDeniedError(PermissionError):
    pass


class PasswordAuthenticator:
    def __init__(self, users: Optional[Dict[str, str]] = None,
                 path: Optional[str] = None):
        self.users = dict(users or {})
        if path:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line and ":" in line and not line.startswith("#"):
                        u, p = line.split(":", 1)
                        self.users[u] = p

    def authenticate(self, user: str, password: str) -> bool:
        import hmac
        expected = self.users.get(user)
        return expected is not None and hmac.compare_digest(
            expected, password)


class AccessControl:
    """First-match catalog rules; default-deny when rules exist, the
    permissive allow-all when constructed with no rules."""

    def __init__(self, rules: Optional[dict] = None):
        self.catalog_rules: List[dict] = \
            list((rules or {}).get("catalogs", []))

    def can_access_catalog(self, user: str, catalog: str) -> bool:
        if not self.catalog_rules:
            return True
        for rule in self.catalog_rules:
            if "user" in rule and not re.fullmatch(rule["user"],
                                                   user or ""):
                continue
            if "catalog" in rule and not re.fullmatch(rule["catalog"],
                                                      catalog):
                continue
            return bool(rule.get("allow", True))
        return False

    def check_can_access_catalog(self, user: str, catalog: str) -> None:
        if not self.can_access_catalog(user, catalog):
            raise AccessDeniedError(
                f"Access Denied: user {user!r} cannot access catalog "
                f"{catalog!r}")

    def filter_catalogs(self, user: str, catalogs: List[str]) -> List[str]:
        return [c for c in catalogs if self.can_access_catalog(user, c)]


class SecuredCatalogs:
    """CatalogManager view that enforces access control on every
    resolution — the planner/executor path needs no security knowledge
    (the reference injects this the same way: MetadataManager resolves
    through AccessControl-checked connectors)."""

    def __init__(self, inner, user: str, access_control: AccessControl):
        self._inner = inner
        self._user = user
        self._ac = access_control

    def get(self, name: str):
        self._ac.check_can_access_catalog(self._user, name)
        return self._inner.get(name)

    def names(self) -> List[str]:
        return self._ac.filter_catalogs(self._user, self._inner.names())

    def register(self, name: str, connector) -> None:
        self._inner.register(name, connector)

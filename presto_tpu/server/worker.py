"""Worker node: executes plan fragments shipped by a coordinator.

The role of the reference's worker half (reference
presto-main/.../execution/SqlTaskManager.java:85,356 task CRUD keyed by
TaskId; server/TaskResource.java:83,124,240,299,311 REST surface;
execution/buffer/ output buffers with token/ack semantics;
operator/ExchangeClient.java pull exchange). TPU-native split: each task
runs a fragment on the local device engine (exec/local._Executor) over
its assigned splits; exchange pages travel as the binary page wire
format (exec/pages) over HTTP — the DCN data plane — while all
device-side compute inside a task stays XLA.

REST surface (mirrors reference TaskResource):

- ``PUT    /v1/task/{id}``                     create + start a task
- ``GET    /v1/task/{id}``                     status JSON
- ``GET    /v1/task/{id}/results/{buf}/{tok}`` long-poll pages; the
  token acknowledges everything below it (reread-on-retry semantics,
  reference execution/buffer/ClientBuffer token protocol)
- ``DELETE /v1/task/{id}``                     abort
- ``GET    /v1/info``                          node state + heartbeat
- ``PUT    /v1/info/state``                    "SHUTTING_DOWN" drains
  active tasks, then refuses new ones (reference
  server/GracefulShutdownHandler.java:43,73)
"""
from __future__ import annotations

import json
import queue as _queue
import struct
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterator, List, Optional, Tuple

from .._devtools.lockcheck import checked_lock
from ..batch import Batch
from ..connectors.spi import CatalogManager, Split
from ..exec import local as local_exec
from ..exec.backoff import jittered
from ..exec.failpoints import FAILPOINTS, FailpointError
from ..obs.log import LOG
from ..obs.metrics import REGISTRY, TASKS
from ..obs.profiler import hbm_totals, profiled
from ..obs.trace import TRACER
from ..exec.pages import deserialize_page, serialize_page, \
    serialize_partitioned
from ..planner import codec
from ..planner.planner import Session
from ..sql.analyzer import AnalysisError

PAGES_CONTENT_TYPE = "application/x-presto-tpu-pages"

_EXCHANGE_SENT_BYTES = REGISTRY.counter("exchange_sent_bytes_total")
_EXCHANGE_SENT_PAGES = REGISTRY.counter("exchange_sent_pages_total")
_EXCHANGE_RECV_BYTES = REGISTRY.counter("exchange_received_bytes_total")
_EXCHANGE_WAIT = REGISTRY.histogram("exchange_wait_seconds")
_EXCHANGE_SPOOL_FALLBACK = REGISTRY.counter(
    "exchange_spool_fallback_total")
_SPEC_READS = REGISTRY.counter("exchange_speculative_read_total")
_SPEC_REPLAY_WON = REGISTRY.counter(
    "exchange_speculative_replay_won_total")
_SPEC_LIVE_WON = REGISTRY.counter(
    "exchange_speculative_live_won_total")

_query_handles: Dict[str, list] = {}
_query_handles_lock = checked_lock("worker.query_handles")


def _query_handle(query_id: str, serving: Optional[dict] = None):
    from ..exec.taskexec import GLOBAL as scheduler
    with _query_handles_lock:
        ent = _query_handles.get(query_id)
        if ent is None:
            # serving-plane handoff riding the task doc: the admitting
            # resource group's scheduler share + weight, so cluster
            # queries get the same group-weighted device scheduling as
            # LocalRunner queries (first task of the query wins — all
            # of a query's tasks share one admission)
            serving = serving or {}
            handle = scheduler.task(
                query_id, group=str(serving.get("group", "")),
                weight=int(serving.get("weight", 1)),
                label=str(serving.get("label", "")) or None)
            ent = _query_handles[query_id] = [handle, 0]
        ent[1] += 1
        return ent[0]


def _release_query_handle(query_id: str) -> None:
    with _query_handles_lock:
        ent = _query_handles.get(query_id)
        if ent is None:
            return
        ent[1] -= 1
        if ent[1] <= 0:
            del _query_handles[query_id]
            ent[0].close()


def frame_pages(pages: List[bytes]) -> bytes:
    """Length-prefix each page into one body."""
    return b"".join(struct.pack("<I", len(p)) + p for p in pages)


def unframe_pages(body: bytes) -> List[bytes]:
    pages, off = [], 0
    while off < len(body):
        (n,) = struct.unpack_from("<I", body, off)
        pages.append(body[off + 4:off + 4 + n])
        off += 4 + n
    return pages


class OutputBuffer:
    """Per-task partitioned output with token/ack reread semantics.

    Replay storage comes in two flavours:

    - ``spool`` (a :class:`~presto_tpu.exec.spool.SpoolWriter`, set by
      the coordinator when ``retry_policy=TASK`` and spooled exchange
      is on — the default): every page is written through to the
      durable page-addressed spool BEFORE it becomes visible, acked
      pages are dropped from memory (shuffle size is no longer capped
      by worker RAM), and a re-created consumer re-reading from token
      0 is served back out of the spool by token;
    - ``retain=True`` (the PR 5 in-memory fallback, still used when
      ``spool_exchange=false``): acked pages are kept resident.

    Buffers are attempt-versioned by construction: every attempt is
    its own task id with its own buffer (and its own spool page logs),
    so a consumer can never interleave pages from two attempts."""

    def __init__(self, n_buffers: int, retain: bool = False,
                 spool=None):
        self.n = n_buffers
        self.retain = retain and spool is None
        self.spool = spool
        self.pages: List[List[Tuple[int, bytes]]] = \
            [[] for _ in range(n_buffers)]
        self.next_token = [0] * n_buffers
        self.finished = False
        self.failed: Optional[str] = None
        self.cond = threading.Condition()

    def add(self, buffer_id: int, page: bytes) -> None:
        _EXCHANGE_SENT_BYTES.inc(len(page))
        _EXCHANGE_SENT_PAGES.inc()
        if self.spool is not None:
            # durable before visible: next_token only advances on this
            # producer thread, so reading it unlocked is safe; a spool
            # write failure propagates and fails the task (which the
            # coordinator then retries elsewhere)
            self.spool.append(buffer_id, self.next_token[buffer_id],
                              page)
        with self.cond:
            self.pages[buffer_id].append(
                (self.next_token[buffer_id], page))
            self.next_token[buffer_id] += 1
            self.cond.notify_all()

    def add_broadcast(self, page: bytes) -> None:
        _EXCHANGE_SENT_BYTES.inc(len(page) * self.n)
        _EXCHANGE_SENT_PAGES.inc(self.n)
        if self.spool is not None:
            for b in range(self.n):
                self.spool.append(b, self.next_token[b], page)
        with self.cond:
            for b in range(self.n):
                self.pages[b].append((self.next_token[b], page))
                self.next_token[b] += 1
            self.cond.notify_all()

    def finish(self) -> None:
        with self.cond:
            self.finished = True
            self.cond.notify_all()

    def fail(self, message: str) -> None:
        # first failure wins: an abort racing (or following) a real
        # error must not overwrite the diagnostic a late poller needs
        with self.cond:
            if self.failed is None:
                self.failed = message
            self.cond.notify_all()

    def drained(self) -> bool:
        """True when nothing depends on this PROCESS to serve the
        buffer anymore: terminal-failed, or finished with its replay
        copy in the durable spool (consumers re-fetch from there), or
        finished with every in-memory page acked. The drain fast-exit
        gate (WorkerServer.begin_shutdown)."""
        with self.cond:
            if self.failed is not None:
                return True
            if not self.finished:
                return False
            if self.spool is not None:
                return True
            return all(not q for q in self.pages)

    def get(self, buffer_id: int, token: int, max_wait_s: float,
            max_bytes: int = 8 << 20):
        """Ack pages below ``token``, long-poll for pages at/after it.
        Returns (pages, next_token, complete). With a spool attached,
        tokens below the in-memory window (a re-created consumer
        re-reading from 0) are served from the spool."""
        deadline = time.monotonic() + max_wait_s
        with self.cond:
            if not self.retain:
                # ack: drop everything the client has by token
                q = self.pages[buffer_id]
                self.pages[buffer_id] = [e for e in q if e[0] >= token]
            while True:
                if self.failed is not None:
                    raise RuntimeError(self.failed)
                avail = [e for e in self.pages[buffer_id]
                         if e[0] >= token]
                if self.spool is not None and not avail \
                        and token < self.next_token[buffer_id]:
                    # the requested token was produced but already
                    # acked out of memory: replay from the spool
                    # (outside the lock — disk reads must not block
                    # the producer)
                    break
                if avail:
                    if self.spool is not None and avail[0][0] != token:
                        # gap below memory (acked away): spool replay.
                        # Spool-less buffers keep the legacy behavior
                        # (serve what memory holds) — they have no
                        # second copy to consult.
                        break
                    out, size = [], 0
                    for t, p in avail:
                        out.append(p)
                        size += len(p)
                        if size >= max_bytes:
                            break
                    nxt = token + len(out)
                    return out, nxt, False
                if self.finished:
                    return [], token, True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self.cond.wait(remaining)
        pages, nxt = self.spool.store.read_pages(
            self.spool.query_id, self.spool.task_id, buffer_id, token,
            max_bytes)
        return pages, nxt, False


class ExchangeFailedError(RuntimeError):
    """A pull exchange lost its upstream. Distinguishable from a plain
    timeout, and the message embeds the upstream TASK id — the
    coordinator's retry layer parses it out of the failed consumer's
    status doc to know *which* upstream attempt to replace."""

    def __init__(self, message: str, task_id: Optional[str] = None,
                 url: Optional[str] = None):
        super().__init__(message)
        self.task_id = task_id
        self.url = url


class ExchangeClient:
    """Pulls pages from every task of an upstream fragment (reference
    operator/ExchangeClient.java:55 + HttpPageBufferClient.java:88):
    one prefetch thread per upstream location, merged into one queue.

    Failure semantics (the retry layer's feed): an HTTP error from the
    upstream (its buffer failed, or the task is gone) fails the pull
    IMMEDIATELY; transport errors (dead worker process) fail after
    ``fail_fast_s`` of consecutive failures rather than the old
    generic 300 s deadline — both as :class:`ExchangeFailedError`
    naming the upstream task."""

    #: consecutive-transport-failure budget before an upstream is
    #: declared lost (session property ``exchange_failure_timeout_s``)
    TRANSPORT_FAILURE_TIMEOUT_S = 45.0

    def __init__(self, locations: List[str], buffer_id: int,
                 timeout_s: float = 300.0,
                 fail_fast_s: Optional[float] = None,
                 cancel_event: Optional[threading.Event] = None,
                 speculative: bool = True,
                 stall_handle=None):
        self.locations = locations
        self.buffer_id = buffer_id
        self.timeout_s = timeout_s
        self.fail_fast_s = (self.TRANSPORT_FAILURE_TIMEOUT_S
                            if fail_fast_s is None else float(fail_fast_s))
        #: session property ``speculative_spool_reads``: on a transport
        #: failure with a committed spool copy, race the spool replay
        #: against resumed live pulls instead of committing to either
        self.speculative = bool(speculative)
        #: abort propagation: a DELETEd task must stop waiting on its
        #: upstreams NOW — an exchange wait runs inside a device-
        #: scheduler quantum, and a cancelled task parked there would
        #: hold the device hostage for the whole transport window
        self.cancel_event = cancel_event
        #: the consuming task's device-scheduler handle: a blocking
        #: wait on remote pages releases the device through
        #: ``DeviceScheduler.stalled`` — holding it while parked on
        #: another worker's output deadlocks multi-process clusters
        #: (each worker's device held by a consumer whose producer is
        #: starved behind it on the peer)
        self.stall_handle = stall_handle
        self.queue: "_queue.Queue" = _queue.Queue(maxsize=64)
        self.stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._pull, args=(u,), daemon=True)
            for u in locations
        ]

    def _drain_spool(self, task_id: str, token: int) -> Optional[bool]:
        """Serve the remainder of this upstream from the durable spool
        when the attempt's completion marker is present (the producing
        worker drained-and-exited, or died after finishing). Returns
        True when fully drained, None when the spool has no committed
        copy (caller keeps its normal retry semantics); raises
        :class:`ExchangeFailedError` on a corrupt page — the retry
        layer's cue to re-run the producer."""
        from ..exec.spool import SPOOL, SpoolCorruptionError
        query_id = task_id.split(".")[0]
        tokens = SPOOL.finished_tokens(query_id, task_id)
        if tokens is None or self.buffer_id >= len(tokens):
            return None
        _EXCHANGE_SPOOL_FALLBACK.inc()
        end = tokens[self.buffer_id]
        while token < end:
            try:
                pages, nxt = SPOOL.read_pages(
                    query_id, task_id, self.buffer_id, token)
            except (SpoolCorruptionError, FailpointError) as e:
                raise ExchangeFailedError(
                    f"upstream task {task_id} spool replay failed: "
                    f"{e}", task_id=task_id) from None
            if nxt == token:
                # the marker promised more tokens than the page log
                # holds: the spool copy is incomplete/damaged
                raise ExchangeFailedError(
                    f"upstream task {task_id} spool replay failed: "
                    f"page log ends at token {token} of {end}",
                    task_id=task_id)
            for page in pages:
                _EXCHANGE_RECV_BYTES.inc(len(page))
                self.queue.put(page)
            token = nxt
        return True

    def _replay_arm(self, query_id: str, task_id: str, token: int,
                    end: int, cancel: threading.Event,
                    results: "_queue.Queue") -> None:
        """Speculative-race arm 1: buffer the remainder from the spool
        (NOT into the consumer queue — the main thread enqueues only
        the winner's pages)."""
        from ..exec.spool import SPOOL, SpoolCorruptionError
        buf: List[bytes] = []
        try:
            FAILPOINTS.hit("exchange.spec_replay", key=task_id,
                           task_id=task_id)
            while token < end:
                if cancel.is_set():
                    return
                try:
                    pages, nxt = SPOOL.read_pages(
                        query_id, task_id, self.buffer_id, token)
                except (SpoolCorruptionError, FailpointError) as e:
                    # a committed-but-damaged copy is decisive: the
                    # producer must re-run no matter what the live arm
                    # finds — surface it as the race verdict
                    results.put(("replay", None, ExchangeFailedError(
                        f"upstream task {task_id} spool replay "
                        f"failed: {e}", task_id=task_id), True))
                    return
                if nxt == token:
                    results.put(("replay", None, ExchangeFailedError(
                        f"upstream task {task_id} spool replay "
                        f"failed: page log ends at token {token} "
                        f"of {end}", task_id=task_id), True))
                    return
                buf.extend(pages)
                token = nxt
            results.put(("replay", buf, None, False))
        except FailpointError as e:
            results.put(("replay", None, ExchangeFailedError(
                f"upstream task {task_id} spool replay failed: {e}",
                task_id=task_id), False))

    def _live_arm(self, url: str, task_id: str, token: int,
                  cancel: threading.Event,
                  results: "_queue.Queue") -> None:
        """Speculative-race arm 2: resume pulling from the (possibly
        merely slow or restarting) live worker, buffering pages until
        the upstream reports complete."""
        buf: List[bytes] = []
        deadline = time.monotonic() + self.fail_fast_s
        while not cancel.is_set() and not self.stop.is_set():
            try:
                FAILPOINTS.hit("exchange.spec_live", key=url,
                               task_id=task_id)
                FAILPOINTS.hit("exchange.pull", key=url,
                               task_id=task_id)
                req = urllib.request.Request(
                    f"{url}/results/{self.buffer_id}/{token}"
                    f"?max_wait=2")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    body = resp.read()
                    complete = resp.headers.get(
                        "X-Buffer-Complete") == "true"
                    token = int(resp.headers.get("X-Next-Token", token))
            except (FailpointError, urllib.error.HTTPError) as e:
                # injected loss, or the upstream answered and refused:
                # the live arm is out of the race for good
                results.put(("live", None, e, False))
                return
            except Exception as e:
                if time.monotonic() >= deadline:
                    results.put(("live", None, e, False))
                    return
                time.sleep(jittered(0.2))
                continue
            buf.extend(unframe_pages(body))
            if complete:
                results.put(("live", buf, None, False))
                return
        # cancelled: the replay arm already won

    def _race_spool(self, url: str, task_id: str,
                    token: int) -> Optional[bool]:
        """Speculative read: race the durable-spool replay against a
        resumed live pull, first complete remainder wins, loser
        cancelled. Engaged on transport failures when the upstream's
        attempt has a committed spool copy — with an object-store
        backend a replay pays real GCS/S3-style latency, so a worker
        that was merely restarting can beat it; with the producer truly
        gone the replay wins unopposed. Returns True when the
        remainder was enqueued (either arm), None when there is no
        committed copy; raises :class:`ExchangeFailedError` when both
        arms lose (a corrupt spool copy is decisive immediately)."""
        from ..exec.spool import SPOOL
        query_id = task_id.split(".")[0]
        tokens = SPOOL.finished_tokens(query_id, task_id)
        if tokens is None or self.buffer_id >= len(tokens):
            return None
        if not self.speculative:
            return self._drain_spool(task_id, token)
        # the replay ATTEMPT counts as a spool fallback (same meaning
        # as the non-speculative path: a committed copy is being read)
        _EXCHANGE_SPOOL_FALLBACK.inc()
        _SPEC_READS.inc()
        end = tokens[self.buffer_id]
        cancel = threading.Event()
        results: "_queue.Queue" = _queue.Queue()
        arms = [
            threading.Thread(
                target=self._replay_arm,
                args=(query_id, task_id, token, end, cancel, results),
                daemon=True),
            threading.Thread(
                target=self._live_arm,
                args=(url, task_id, token, cancel, results),
                daemon=True),
        ]
        for t in arms:
            t.start()
        errors: List[Exception] = []
        decisive: Optional[Exception] = None
        for _ in range(len(arms)):
            who, buf, err, is_decisive = results.get()
            if buf is not None:
                cancel.set()           # first complete remainder wins
                (_SPEC_REPLAY_WON if who == "replay"
                 else _SPEC_LIVE_WON).inc()
                for page in buf:
                    _EXCHANGE_RECV_BYTES.inc(len(page))
                    self.queue.put(page)
                return True
            if is_decisive:
                cancel.set()
                decisive = err
                break
            errors.append(err)
        cancel.set()
        if decisive is not None:
            raise decisive
        raise ExchangeFailedError(
            f"upstream task {task_id} lost the speculative read on "
            f"both arms: {'; '.join(str(e) for e in errors)}",
            task_id=task_id, url=url)

    def _pull(self, url: str) -> None:
        token = 0
        task_id = url.rsplit("/v1/task/", 1)[-1]
        deadline = time.monotonic() + self.timeout_s
        first_err: Optional[float] = None
        try:
            while not self.stop.is_set():
                try:
                    FAILPOINTS.hit("exchange.pull", key=url,
                                   task_id=task_id)
                except FailpointError as e:
                    raise ExchangeFailedError(
                        f"exchange pull from upstream task {task_id} "
                        f"failed: {e}", task_id=task_id, url=url) \
                        from None
                req = urllib.request.Request(
                    f"{url}/results/{self.buffer_id}/{token}?max_wait=2")
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        body = resp.read()
                        complete = resp.headers.get(
                            "X-Buffer-Complete") == "true"
                        token = int(resp.headers.get("X-Next-Token",
                                                     token))
                except urllib.error.HTTPError as e:
                    # the upstream answered: its task failed, was
                    # aborted, or is unknown — before declaring it
                    # dead, check the durable spool: a drained (or
                    # restarted) worker's committed attempt replays
                    # from storage with no producer re-run
                    if self._drain_spool(task_id, token):
                        break
                    try:
                        detail = json.loads(
                            e.read() or b"{}").get("error") or ""
                    except Exception:
                        detail = ""
                    raise ExchangeFailedError(
                        f"upstream task {task_id} failed: HTTP "
                        f"{e.code}: {detail or e.reason}",
                        task_id=task_id, url=url) from None
                except Exception as e:  # transport: bounded retry
                    # a dead producer whose attempt committed its
                    # spool needs no retry window at all — race the
                    # spool replay against a resumed live pull (the
                    # worker may be merely restarting; with an
                    # object-store spool the replay is not free)
                    if self._race_spool(url, task_id, token):
                        break
                    now = time.monotonic()
                    if first_err is None:
                        first_err = now
                    if now - first_err >= self.fail_fast_s \
                            or now > deadline:
                        raise ExchangeFailedError(
                            f"upstream task {task_id} unreachable "
                            f"for {now - first_err:.1f}s: {e}",
                            task_id=task_id, url=url) from None
                    time.sleep(jittered(0.2))
                    continue
                first_err = None
                deadline = time.monotonic() + self.timeout_s
                for page in unframe_pages(body):
                    _EXCHANGE_RECV_BYTES.inc(len(page))
                    self.queue.put(page)
                if complete:
                    break
        except BaseException as e:   # surfaced on the consumer side
            self.queue.put(e)
        finally:
            self.queue.put(None)   # this upstream is drained

    def _next(self):
        """Next queue item; waits cancellably and records the wait as
        an input stall (credited back to the device scheduler — time
        blocked on the network is not device time)."""
        try:
            return self.queue.get_nowait()
        except _queue.Empty:
            pass
        from ..exec import taskexec
        sched = (self.stall_handle.scheduler
                 if self.stall_handle is not None else taskexec.GLOBAL)
        t0 = time.monotonic()
        try:
            with sched.stalled(self.stall_handle):
                while True:
                    if self.cancel_event is not None \
                            and self.cancel_event.is_set():
                        from ..errors import QueryCancelledError
                        raise QueryCancelledError("task aborted")
                    try:
                        return self.queue.get(timeout=0.25)
                    except _queue.Empty:
                        continue
        finally:
            dt = time.monotonic() - t0
            _EXCHANGE_WAIT.observe(dt)
            taskexec.GLOBAL.note_stall(dt)

    def batches(self) -> Iterator[Batch]:
        for t in self._threads:
            t.start()
        done = 0
        try:
            while done < len(self._threads):
                item = self._next()
                if item is None:
                    done += 1
                    continue
                if isinstance(item, Exception):
                    raise item
                yield deserialize_page(item)
        finally:
            self.stop.set()


class _TaskExecutor(local_exec._Executor):
    """Local device engine bound to one task: scans read only the task's
    assigned splits; RemoteSourceNodes pull from upstream tasks."""

    def __init__(self, session: Session, rows_per_batch: int,
                 splits: List[Split],
                 sources: Dict[int, List[str]], partition: int):
        super().__init__(session, rows_per_batch)
        self.assigned_splits = splits
        self.sources = sources
        self.partition = partition

    def _TableScanNode(self, node) -> Iterator[Batch]:
        # same cache + prefetch pipeline as the local executor: repeated
        # queries hit device memory on every node, and cold splits
        # decode/stage on background threads while this task's kernels
        # run (exec/scancache.py)
        from ..exec import scancache, taskexec
        conn = self.session.catalogs.get(node.catalog)
        opts = scancache.options_from_session(self.session)
        it = scancache.scan_splits(
            conn, node.catalog, list(node.columns),
            list(self.assigned_splits), self._scan_pushdown_fn(node),
            self.rows_per_batch, opts, stats=self.stats,
            static_pushdown=node.pushdown or None)
        # modeled device floor per SCANNED batch (no-op unless
        # PRESTO_TPU_DEVICE_FLOOR_MS is set): the output buffer above
        # this node coalesces pages, so the quantum-level floor alone
        # would bill a worker by what it EMITS, not what it processes
        sentinel = object()
        while True:
            t0 = time.perf_counter()
            b = next(it, sentinel)
            if b is sentinel:
                return
            taskexec.device_floor_pad(time.perf_counter() - t0)
            yield b

    def _RemoteSourceNode(self, node) -> Iterator[Batch]:
        locations: List[str] = []
        for fid in node.fragment_ids:
            locations.extend(self.sources.get(fid, ()))
        fail_fast = float(self.session.properties.get(
            "exchange_failure_timeout_s",
            ExchangeClient.TRANSPORT_FAILURE_TIMEOUT_S))
        from ..exec.local import bool_property
        client = ExchangeClient(locations, self.partition,
                                fail_fast_s=fail_fast,
                                cancel_event=getattr(
                                    self, "cancel_event", None),
                                speculative=bool_property(
                                    self.session,
                                    "speculative_spool_reads", True),
                                stall_handle=getattr(
                                    self, "task_handle", None))
        schema = local_exec._plan_schema(node)
        for b in client.batches():
            # positional contract: upstream emits the same field layout
            yield Batch(schema, b.columns, b.row_mask)


class Task:
    """One fragment execution (reference execution/SqlTask.java +
    TaskStateMachine states PLANNED/RUNNING/FINISHED/FAILED/ABORTED)."""

    def __init__(self, task_id: str, doc: dict, catalogs: CatalogManager,
                 node_id: str = ""):
        self.task_id = task_id
        self.node_id = node_id
        self.state = "PLANNED"
        self.error: Optional[str] = None
        #: wire-carried span context (coordinator trace/parent ids) so
        #: this task's spans stitch into the query trace
        self.trace_ctx = doc.get("trace")
        self.started_at: Optional[float] = None
        self.elapsed_ms = 0.0
        #: output accounting, surfaced in status docs (the feed of the
        #: coordinator's progress/straggler/skew monitor) and in
        #: system.runtime.tasks
        self.rows_out = 0
        self.bytes_out = 0
        self.root = codec.decode(doc["fragment"])
        self.output_kind = doc["output"]["kind"]
        self.output_keys = list(doc["output"].get("keys", ()))
        n_buffers = int(doc["output"]["n_buffers"])
        #: spooled exchange (exec/spool.py): the coordinator sets
        #: output.spool for non-root fragments under retry_policy=TASK
        #: — every page becomes durable and replayable by token, so
        #: retries/speculation/drain never need this process alive to
        #: re-read this attempt's output
        self.spool_writer = None
        if bool(doc["output"].get("spool", False)):
            from ..exec.spool import SPOOL
            self.spool_writer = SPOOL.writer(
                task_id.split(".")[0], task_id, n_buffers)
        self.buffer = OutputBuffer(
            n_buffers,
            retain=bool(doc["output"].get("retain", False)),
            spool=self.spool_writer)
        #: set by DELETE-abort; checked between quanta (and, via the
        #: executor's cancel_event, inside scans) so an aborted task
        #: stops burning device time instead of running to completion
        self._abort = threading.Event()
        self.splits = [codec.decode(s) for s in doc.get("splits", [])]
        self.sources = {int(k): list(v)
                        for k, v in doc.get("sources", {}).items()}
        self.partition = int(doc.get("partition", 0))
        #: group scheduling handoff (serving/groups.py via the task
        #: doc): {"group", "weight", "label"} or None
        self.serving = doc.get("serving")
        session_doc = doc.get("session", {})
        self.session = Session(
            catalogs=catalogs,
            catalog=session_doc.get("catalog", "tpch"),
            schema=session_doc.get("schema", "default"),
            properties=dict(session_doc.get("properties", {})))
        self.init_values = list(codec.decode(doc.get("init_values", [])))
        self.rows_per_batch = int(doc.get("rows_per_batch", 1 << 17))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._register()

    def _task_ids(self):
        """(query_id, stage_id) parsed from 'qid.fid.part'."""
        parts = self.task_id.split(".")
        qid = parts[0]
        fid = int(parts[1]) if len(parts) > 2 and parts[1].isdigit() else 0
        return qid, fid

    def _register(self) -> None:
        qid, fid = self._task_ids()
        TASKS.update(self.task_id, query_id=qid, stage_id=fid,
                     partition=self.partition, node_id=self.node_id,
                     state=self.state, elapsed_ms=self._elapsed_now(),
                     output_rows=self.rows_out,
                     output_bytes=self.bytes_out)

    def _elapsed_now(self) -> float:
        """Live elapsed for RUNNING tasks; frozen value once terminal."""
        if self.state == "RUNNING" and self.started_at is not None:
            return (time.monotonic() - self.started_at) * 1e3
        return self.elapsed_ms

    def _set_state(self, state: str) -> None:
        self.state = state
        if self.started_at is not None:
            self.elapsed_ms = (time.monotonic() - self.started_at) * 1e3
        self._register()

    def start(self) -> None:
        self.started_at = time.monotonic()
        self._set_state("RUNNING")
        self._thread.start()

    def _run(self) -> None:
        # one shared handle per QUERY: pipeline stages of a query feed
        # each other pages and must never serialize behind their own
        # query's scheduler turn (reference TaskExecutor groups splits
        # under a per-task TaskHandle the same way)
        qid, fid = self._task_ids()
        handle = _query_handle(qid, self.serving)
        try:
            with TRACER.task_span(self.trace_ctx, "task",
                                  task_id=self.task_id, query_id=qid,
                                  stage_id=fid,
                                  partition=self.partition,
                                  node_id=self.node_id):
                FAILPOINTS.hit("worker.task_run",
                               key=f"{self.task_id}@{self.node_id}",
                               task_id=self.task_id,
                               node_id=self.node_id)
                ex = _TaskExecutor(self.session, self.rows_per_batch,
                                   self.splits, self.sources,
                                   self.partition)
                self.pool = ex.pool  # visible to /v1/info memory report
                # abort propagation: the executor checks this event per
                # scan batch, so a DELETE interrupts a task mid-scan
                ex.cancel_event = self._abort
                # exchange consumers release the device while parked on
                # remote pages (DeviceScheduler.stalled via this handle)
                ex.task_handle = handle
                ex.init_values = self.init_values
                ex.mark_shared([self.root])
                # fair device scheduling across concurrent tasks: one
                # quantum per produced batch (reference TaskExecutor
                # time slicing)
                # `profile` session prop rides the task doc: this
                # task's jit dispatches get device-time bracketing and
                # land in the worker's obs.profiler.EXECUTABLES (and
                # its system.runtime.executables table)
                from ..exec.local import bool_property
                profile_ctx = profiled(
                    bool_property(self.session, "profile", False))
                it = ex.run(self.root)
                sentinel = object()
                with profile_ctx:
                    while True:
                        if self._abort.is_set():
                            from ..errors import QueryCancelledError
                            raise QueryCancelledError("task aborted")
                        batch = handle.scheduler.run_quantum(
                            handle, lambda: next(it, sentinel))
                        if batch is sentinel:
                            break
                        live = batch.host_count()
                        if live == 0:
                            continue
                        self.rows_out += live
                        if self.output_kind == "partition":
                            pages = serialize_partitioned(
                                batch, self.output_keys, self.buffer.n)
                            for b, page in enumerate(pages):
                                if page is not None:
                                    self.bytes_out += len(page)
                                    self.buffer.add(b, page)
                        elif self.output_kind == "broadcast":
                            page = serialize_page(batch)
                            self.bytes_out += len(page)
                            self.buffer.add_broadcast(page)
                        else:   # single
                            page = serialize_page(batch)
                            self.bytes_out += len(page)
                            self.buffer.add(0, page)
                ex.check_errors()
            if self.spool_writer is not None:
                # commit the spool BEFORE announcing FINISHED: a
                # consumer (or the coordinator's lost-task probe) that
                # sees the completion marker can trust the page logs
                self.spool_writer.finish(self.buffer.next_token)
            self.buffer.finish()
            self._set_state("FINISHED")
        except Exception as e:   # noqa: BLE001 - reported to coordinator
            if self.spool_writer is not None:
                # a failed/aborted attempt's partial page logs are
                # garbage: drop them now instead of squatting on
                # spool.max-bytes until query-end GC
                self.spool_writer.abandon()
            if self._abort.is_set():
                # a DELETE-abort interrupted the run loop: ABORTED (set
                # by abort()) is the verdict, not FAILED, and the
                # buffer already carries "task aborted"
                self.buffer.fail("task aborted")
            else:
                self.error = f"{type(e).__name__}: {e}"
                self._set_state("FAILED")
                self.buffer.fail(self.error)
                LOG.log("task_failed", query_id=qid,
                        task_id=self.task_id, node_id=self.node_id,
                        error=self.error)
        finally:
            _release_query_handle(qid)

    def abort(self) -> None:
        if self.state in ("PLANNED", "RUNNING"):
            self._abort.set()
            self._set_state("ABORTED")
            self.error = self.error or "task aborted"
            self.buffer.fail("task aborted")

    def status(self, include_spans: bool = False) -> dict:
        doc = {"taskId": self.task_id, "state": self.state,
               "error": self.error,
               "elapsedMs": round(self._elapsed_now(), 1),
               "rowsOut": self.rows_out, "bytesOut": self.bytes_out}
        self._register()     # status polls refresh system.runtime.tasks
        if include_spans and isinstance(self.trace_ctx, dict):
            # span harvest: the coordinator pulls this worker's spans for
            # the query's trace after completion and merges them into its
            # own ring (dedup by span id — in-process workers share it)
            doc["spans"] = TRACER.export(
                trace_id=self.trace_ctx.get("traceId"))
        return doc


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # quiet
        pass

    @property
    def worker(self) -> "WorkerServer":
        return self.server.worker    # type: ignore[attr-defined]

    def _json(self, code: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        parts = self.path.split("?")[0].strip("/").split("/")
        if parts[:2] == ["v1", "info"]:
            self._json(200, self.worker.info())
            return
        if parts[:3] == ["v1", "metrics", "history"]:
            # windowed range reads (obs/timeseries.py); must precede
            # the prefix match below — ["v1","metrics"] would swallow
            # the history path
            from ..obs.timeseries import TIMESERIES
            qs = self.path.split("?", 1)[1] if "?" in self.path else ""
            code, doc = TIMESERIES.history_doc(qs)
            self._json(code, doc)
            return
        if parts[:2] == ["v1", "metrics"]:
            # Prometheus scrape surface: the process-wide registry in
            # text exposition format (obs/exposition.py)
            from ..obs.exposition import render_exposition
            body = render_exposition(REGISTRY).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts[:2] == ["v1", "task"] and len(parts) == 3:
            task = self.worker.tasks.get(parts[2])
            if task is None:
                tomb = self.worker.done.get(parts[2])
                if tomb is not None:
                    self._json(200, dict(tomb))
                    return
                self._json(404, {"error": "no such task"})
                return
            self._json(200, task.status(
                include_spans="spans=1" in self.path))
            return
        if (parts[:2] == ["v1", "task"] and len(parts) == 6
                and parts[3] == "results"):
            task = self.worker.tasks.get(parts[2])
            if task is None:
                # terminal-state tombstone: a late poller (an exchange
                # client that out-lived the task) gets the REAL verdict
                # — a clean complete page for FINISHED, the persisted
                # failure for FAILED/ABORTED — never a bare 404 it
                # would misread as a transient drop
                tomb = self.worker.done.get(parts[2])
                if tomb is None:
                    self._json(404, {"error": "no such task"})
                    return
                if tomb.get("state") == "FINISHED":
                    self.send_response(200)
                    self.send_header("Content-Type", PAGES_CONTENT_TYPE)
                    self.send_header("Content-Length", "0")
                    self.send_header("X-Next-Token", parts[5])
                    self.send_header("X-Buffer-Complete", "true")
                    self.end_headers()
                    return
                self._json(500, {"error": tomb.get("error")
                                 or f"task {tomb.get('state')}"})
                return
            buf, token = int(parts[4]), int(parts[5])
            wait = 2.0
            if "max_wait=" in self.path:
                wait = float(self.path.split("max_wait=")[1].split("&")[0])
            try:
                pages, nxt, complete = task.buffer.get(buf, token, wait)
            except RuntimeError as e:
                self._json(500, {"error": str(e)})
                return
            body = frame_pages(pages)
            self.send_response(200)
            self.send_header("Content-Type", PAGES_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Next-Token", str(nxt))
            self.send_header("X-Buffer-Complete",
                             "true" if complete else "false")
            self.end_headers()
            self.wfile.write(body)
            return
        self._json(404, {"error": "not found"})

    def do_PUT(self) -> None:
        parts = self.path.strip("/").split("/")
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n) if n else b""
        if parts[:2] == ["v1", "info"] and parts[2:] == ["state"]:
            state = json.loads(body) if body else ""
            if state == "SHUTTING_DOWN":
                self.worker.begin_shutdown()
                self._json(200, {"state": "SHUTTING_DOWN"})
            else:
                self._json(400, {"error": f"bad state {state!r}"})
            return
        if parts[:2] == ["v1", "task"] and len(parts) == 3:
            if self.worker.shutting_down:
                self._json(503, {"error": "worker is shutting down"})
                return
            try:
                task = self.worker.create_task(parts[2],
                                               json.loads(body))
            except (KeyError, ValueError, AnalysisError) as e:
                self._json(400, {"error": str(e)})
                return
            self._json(200, task.status())
            return
        self._json(404, {"error": "not found"})

    def do_DELETE(self) -> None:
        parts = self.path.strip("/").split("/")
        if parts[:2] == ["v1", "task"] and len(parts) == 3:
            task = self.worker.tasks.pop(parts[2], None)
            if task is not None:
                task.abort()
                self.worker.retire(task)
            self._json(200, {"aborted": task is not None})
            return
        if parts[:2] == ["v1", "query"] and len(parts) == 3:
            n = self.worker.abort_query(parts[2])
            self._json(200, {"aborted_tasks": n})
            return
        if parts[:2] == ["v1", "spool"] and len(parts) == 3:
            # per-query spool GC (coordinator-driven at query end; the
            # abort path releases through abort_query)
            from ..exec.spool import SPOOL
            self._json(200,
                       {"released_bytes": SPOOL.release_query(parts[2])})
            return
        self._json(404, {"error": "not found"})


class WorkerServer:
    def __init__(self, catalogs: Optional[CatalogManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 node_id: Optional[str] = None, tpch_sf: float = 0.01,
                 drain_grace_s: float = 5.0):
        if catalogs is None:
            from ..connectors.memory import MemoryConnector
            from ..connectors.system import SystemConnector
            from ..connectors.tpcds import TpcdsConnector
            from ..connectors.tpch import TpchConnector
            catalogs = CatalogManager()
            catalogs.register("tpch", TpchConnector(sf=tpch_sf))
            catalogs.register("tpcds", TpcdsConnector(sf=tpch_sf))
            catalogs.register("memory", MemoryConnector())
            catalogs.register("system", SystemConnector(catalogs))
        self.catalogs = catalogs
        self.tasks: Dict[str, Task] = {}
        #: terminal-state tombstones of deleted tasks (bounded), so late
        #: status/results polls see the real verdict instead of a 404
        self.done: "OrderedDict[str, dict]" = OrderedDict()
        self.started_at = time.time()
        self.shutting_down = False
        #: bounded consumer-drain window after active tasks finish:
        #: spool-backed buffers skip it entirely (consumers re-fetch
        #: already-acked pages from the durable spool), so a draining
        #: worker EXITS within this grace instead of lingering until
        #: every downstream consumer completes
        self.drain_grace_s = float(drain_grace_s)
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.worker = self   # type: ignore[attr-defined]
        self.port = self.httpd.server_address[1]
        self.node_id = node_id or f"worker-{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._announcer = None
        #: set once stop() ran — subprocess workers (the autoscaler's
        #: LocalProcessProvider) park their main thread on it so a
        #: drained worker EXITS its process instead of sleeping forever
        self.stopped = threading.Event()

    def start(self) -> None:
        # workers carry the same windowed-history surface as the
        # coordinator: the process-wide sampler feeds /v1/metrics/history
        from ..obs.timeseries import TIMESERIES
        TIMESERIES.ensure_started()
        self._thread.start()

    def start_announcing(self, discovery_uri,
                         advertised_host: str = "127.0.0.1",
                         interval_s: float = 5.0) -> None:
        """Join a coordinator by announcement (reference workers announce
        via discovery and may join any time — elastic scale-out).
        ``discovery_uri`` may be a list (or a comma-separated string)
        of coordinator URIs: a fleet worker announces to every
        coordinator each beat, making ONE worker pool visible to all
        fleet members."""
        from ..exec.discovery import Announcer
        if isinstance(discovery_uri, str) and "," in discovery_uri:
            discovery_uri = [u.strip() for u in discovery_uri.split(",")
                             if u.strip()]
        self._announcer = Announcer(
            discovery_uri, self.node_id,
            f"http://{advertised_host}:{self.port}", interval_s)
        self._announcer.start()

    def stop(self) -> None:
        if self._announcer is not None:
            # explicit leave: a final GONE announcement removes this
            # node from discovery immediately (elastic scale-in),
            # instead of waiting out the announcement TTL
            self._announcer.deregister()
        self.httpd.shutdown()
        # release the listening socket too: a stopped worker must
        # REFUSE connections — a bound-but-unserved socket makes every
        # peer (exchange pulls, coordinator probes) hang to its full
        # timeout instead of failing over to the spool instantly
        self.httpd.server_close()
        self.stopped.set()

    def create_task(self, task_id: str, doc: dict) -> Task:
        # idempotent: the coordinator's transport retries task PUTs, so
        # a re-delivered create must return the existing task instead of
        # spawning a duplicate executor over the same splits (reference
        # SqlTaskManager.updateTask is an upsert keyed by TaskId)
        existing = self.tasks.get(task_id)
        if existing is not None:
            return existing
        self.done.pop(task_id, None)
        task = Task(task_id, doc, self.catalogs, node_id=self.node_id)
        self.tasks[task_id] = task
        task.start()
        return task

    def retire(self, task: Task) -> None:
        """Record a deleted task's terminal state (bounded tombstone
        map — the persistence half of OutputBuffer failure state)."""
        self.done[task.task_id] = {
            "taskId": task.task_id, "state": task.state,
            "error": task.error,
            "elapsedMs": round(task._elapsed_now(), 1),
            "rowsOut": task.rows_out, "bytesOut": task.bytes_out,
        }
        while len(self.done) > 512:
            self.done.popitem(last=False)

    def info(self) -> dict:
        # per-query reserved bytes ride the heartbeat payload — the feed
        # of the coordinator's cluster memory manager (reference
        # memory/ClusterMemoryManager.java polls worker memory info)
        queries: Dict[str, int] = {}
        for t in list(self.tasks.values()):
            pool = getattr(t, "pool", None)
            if pool is None or t.state != "RUNNING":
                continue
            qid = t.task_id.split(".")[0]
            queries[qid] = queries.get(qid, 0) + int(pool.reserved)
        return {
            "nodeId": self.node_id,
            "state": "SHUTTING_DOWN" if self.shutting_down else "ACTIVE",
            "uptime_s": time.time() - self.started_at,
            "tasks": {s: sum(1 for t in list(self.tasks.values())
                             if t.state == s)
                      for s in ("RUNNING", "FINISHED", "FAILED")},
            "queryMemory": queries,
            # pool high-water for the coordinator's node federator
            # (process-wide gauge: in-process test workers share it)
            "memPoolPeakBytes": int(
                REGISTRY.gauge("memory_pool_peak_bytes").value),
            # HBM sample riding the heartbeat: device.memory_stats()
            # summed over local devices AND published as per-device
            # hbm_in_use_bytes/hbm_peak_bytes gauges on this worker's
            # /v1/metrics (zeros on stats-less backends like XLA:CPU)
            "hbm": hbm_totals(),
        }

    def abort_query(self, query_id: str) -> int:
        """Query-level abort: every task of the query is aborted AND
        freed from the task map (tombstoned), so a cancelled query
        releases its buffers instead of squatting until eviction."""
        n = 0
        for t in list(self.tasks.values()):
            if t.task_id.split(".")[0] != query_id:
                continue
            if t.state in ("PLANNED", "RUNNING"):
                t.abort()
                n += 1
            self.tasks.pop(t.task_id, None)
            self.retire(t)
        # wake any task thread of this query blocked in the device
        # scheduler's wait queue (exec/taskexec.py): the shared
        # per-query handle carries the abort
        with _query_handles_lock:
            ent = _query_handles.get(query_id)
            if ent is not None:
                ent[0].aborted.set()
        # an aborted query's spooled pages can never be read again —
        # GC now so aborts don't orphan per-query spool directories
        from ..exec.spool import SPOOL
        SPOOL.release_query(query_id)
        return n

    def begin_shutdown(self) -> None:
        """Drain: refuse new tasks, wait for active ones to finish
        (their output commits to the spool), give un-spooled buffers a
        bounded ``drain_grace_s`` for consumers to pull, then stop —
        the worker EXITS without waiting for downstream completion;
        consumers re-fetch already-acked pages from the durable spool
        (ExchangeClient spool fallback)."""
        self.shutting_down = True
        if self._announcer is not None:
            # push the drain state to discovery immediately — the
            # scheduler must stop assigning before the next heartbeat
            self._announcer.set_state("SHUTTING_DOWN")

        def drain():
            # snapshot per round: abort_query pops entries from other
            # threads, and a dict-changed-mid-iteration RuntimeError
            # here would silently kill the drain thread — the worker
            # would linger forever with stop() never called
            while any(t.state in ("PLANNED", "RUNNING")
                      for t in list(self.tasks.values())):
                time.sleep(0.1)
            grace = time.monotonic() + self.drain_grace_s
            while time.monotonic() < grace \
                    and any(not t.buffer.drained()
                            for t in list(self.tasks.values())):
                time.sleep(0.1)
            self.stop()
        threading.Thread(target=drain, daemon=True).start()


def main() -> None:
    import argparse
    p = argparse.ArgumentParser(description="presto_tpu worker node")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--tpch-sf", type=float, default=0.01)
    p.add_argument("--node-id", default=None)
    p.add_argument("--etc-dir", default=None,
                   help="config directory (config.properties + catalog/)")
    p.add_argument("--spool-dir", default=None,
                   help="exchange spool directory (overrides etc "
                        "spool.dir; point every node at shared storage)")
    p.add_argument("--coordinator", default=None,
                   help="coordinator URL to announce to "
                        "(overrides etc discovery.uri)")
    args = p.parse_args()
    try:
        # ops hook: SIGUSR1 dumps every thread's stack to stderr — the
        # way to see what a wedged worker is waiting on without
        # attaching a debugger to the subprocess
        import faulthandler
        import signal
        faulthandler.register(signal.SIGUSR1)
    except (ImportError, AttributeError, ValueError):
        pass
    catalogs = None
    node_id = args.node_id
    port = args.port
    discovery_uri = args.coordinator
    spool_dir = args.spool_dir
    if args.etc_dir:
        from ..config import load_catalogs, load_node_config
        cfg = load_node_config(args.etc_dir)
        catalogs = load_catalogs(args.etc_dir)
        node_id = node_id or cfg.node_id
        port = port or cfg.http_port
        discovery_uri = discovery_uri or cfg.discovery_uri
        if cfg.failpoints:
            FAILPOINTS.configure_from_spec(cfg.failpoints)
        spool_dir = spool_dir or cfg.spool_dir
        from ..config import configure_spool
        configure_spool(cfg, directory=spool_dir)
    elif spool_dir:
        from ..exec.spool import SPOOL
        SPOOL.configure(directory=spool_dir)
    w = WorkerServer(catalogs=catalogs, host=args.host, port=port,
                     node_id=node_id, tpch_sf=args.tpch_sf)
    print(json.dumps({"nodeId": w.node_id, "port": w.port}), flush=True)
    w.start()
    if discovery_uri:
        w.start_announcing(discovery_uri, advertised_host=args.host)
    try:
        # park until drained: a PUT /v1/info/state SHUTTING_DOWN (the
        # autoscaler's scale-down path) ends in stop(), and the process
        # must exit so its provider can reap it
        while not w.stopped.wait(timeout=3600):
            pass
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

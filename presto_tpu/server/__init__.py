from .protocol import PrestoTpuServer

__all__ = ["PrestoTpuServer"]

from .protocol import PrestoTpuServer, StatementServer
from .resource_groups import (
    QueryQueuedTimeoutError, QueryQueueFullError, ResourceGroupManager,
)

__all__ = ["PrestoTpuServer", "StatementServer", "ResourceGroupManager",
           "QueryQueueFullError", "QueryQueuedTimeoutError"]

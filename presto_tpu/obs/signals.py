"""The autoscaler signals feed: one frozen, typed cluster snapshot.

ROADMAP item 3 ("autoscaler watches resource-group queue depth, p95,
and per-node HBM") needs a *stable input contract* long before the
control loop itself exists.  This module is that contract:
:func:`cluster_signals` assembles one immutable :class:`ClusterSignals`
from surfaces that already exist —

- per-group admission state (queue depth / running) from the live
  resource-group managers (``serving/groups.py``),
- per-group windowed p95 + SLO burn/budget/alert-state from the
  time-series store and SLO tracker (``obs/timeseries.py``,
  ``obs/slo.py``),
- per-node heartbeat age, active tasks, and HBM in-use/peak from the
  node registry (``obs.metrics.NODES``, fed by the cluster heartbeat),
- scan-cache / plan-cache / result-cache pressure from the serving
  cache singletons.

Consumers MUST treat a snapshot as a value: every field is frozen, and
``None`` means "no data yet" (e.g. p95 before two samples exist), never
zero.  ``tools/autoscale_watch.py`` is the demo consumer — a threshold
watcher proving a control loop can drive off this feed without touching
engine internals.

Compatibility promise: fields are only added, never renamed or removed;
new fields always default to ``None``/empty so older consumers keep
working (the same promise the bench JSON schemas make).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .slo import SLO
from .timeseries import TIMESERIES

#: default window (seconds) for the windowed p95 in GroupSignals
SIGNAL_WINDOW_S = 300.0


@dataclass(frozen=True)
class GroupSignals:
    """One resource group's health at the snapshot instant."""
    group: str                       # dotted path, e.g. "serving.dash"
    state: str                       # CAN_RUN | FULL | OVER_SOFT_MEMORY_LIMIT
    running: int
    queued: int
    hard_concurrency_limit: int
    p95_s: Optional[float] = None    # windowed serving latency p95
    burn_short: Optional[float] = None   # shortest-window burn rate
    burn_long: Optional[float] = None    # longest-window burn rate
    error_budget_remaining: Optional[float] = None  # 0..1
    alert_state: str = "OK"          # OK | WARN | PAGE


@dataclass(frozen=True)
class NodeSignals:
    """One worker node's health, from the heartbeat-fed registry."""
    node_id: str
    state: str                       # e.g. "active"
    heartbeat_age_s: float           # inf when never seen
    active_tasks: int = 0
    hbm_in_use_bytes: Optional[int] = None
    hbm_peak_bytes: Optional[int] = None


@dataclass(frozen=True)
class CacheSignals:
    """Serving-cache pressure (0..1 fill fractions where a limit exists)."""
    scan_cache_resident_bytes: int = 0
    scan_cache_limit_bytes: int = 0
    plan_cache_entries: int = 0
    plan_cache_capacity: int = 0
    result_cache_resident_bytes: int = 0
    result_cache_limit_bytes: int = 0

    @property
    def scan_cache_pressure(self) -> float:
        if self.scan_cache_limit_bytes <= 0:
            return 0.0
        return self.scan_cache_resident_bytes / self.scan_cache_limit_bytes

    @property
    def plan_cache_pressure(self) -> float:
        if self.plan_cache_capacity <= 0:
            return 0.0
        return self.plan_cache_entries / self.plan_cache_capacity

    @property
    def result_cache_pressure(self) -> float:
        if self.result_cache_limit_bytes <= 0:
            return 0.0
        return (self.result_cache_resident_bytes
                / self.result_cache_limit_bytes)


@dataclass(frozen=True)
class ClusterSignals:
    """The complete autoscaler input: groups + nodes + caches at ``ts``."""
    ts: float
    groups: Tuple[GroupSignals, ...] = ()
    nodes: Tuple[NodeSignals, ...] = ()
    caches: CacheSignals = field(default_factory=CacheSignals)

    def group(self, path: str) -> Optional[GroupSignals]:
        for g in self.groups:
            if g.group == path:
                return g
        return None

    def node(self, node_id: str) -> Optional[NodeSignals]:
        for n in self.nodes:
            if n.node_id == node_id:
                return n
        return None


def _group_signals(now: float) -> Tuple[GroupSignals, ...]:
    from ..serving.groups import live_managers
    budgets: Dict[str, Tuple] = {}
    for row in SLO.snapshot_rows(now=now):
        # prefer the latency objective's burn for a group with both
        group, objective = row[0], row[1]
        if group not in budgets or objective == "latency":
            budgets[group] = (row[7], row[8], row[9])
    declared = {(o.group, o.objective) for o in SLO.objectives()}
    out = []
    seen = set()
    for mgr in live_managers():
        stack = list(mgr.info())
        while stack:
            g = stack.pop()
            stack.extend(g["subGroups"])
            path = g["id"]
            if path in seen:
                continue
            seen.add(path)
            p95 = TIMESERIES.window_quantile(
                f"serving_latency_seconds.{path}", SIGNAL_WINDOW_S,
                0.95, now=now)
            burn_short, burn_long, budget = budgets.get(
                path, (None, None, None))
            kind = ("latency" if (path, "latency") in declared
                    else "availability")
            out.append(GroupSignals(
                group=path, state=g["state"],
                running=int(g["numRunning"]),
                queued=int(g["numQueued"]),
                hard_concurrency_limit=int(g["hardConcurrencyLimit"]),
                p95_s=p95, burn_short=burn_short, burn_long=burn_long,
                error_budget_remaining=budget,
                alert_state=SLO.state_of(path, kind)))
    out.sort(key=lambda g: g.group)
    return tuple(out)


def _node_signals() -> Tuple[NodeSignals, ...]:
    from .metrics import NODES
    out = []
    for doc in NODES.snapshot():
        out.append(NodeSignals(
            node_id=str(doc.get("node_id", "")),
            state=str(doc.get("state", "unknown")),
            heartbeat_age_s=float(doc.get("heartbeat_age_s",
                                          float("inf"))),
            active_tasks=int(doc.get("active_tasks", 0) or 0),
            hbm_in_use_bytes=doc.get("hbm_in_use_bytes"),
            hbm_peak_bytes=doc.get("hbm_peak_bytes")))
    out.sort(key=lambda n: n.node_id)
    return tuple(out)


def _cache_signals() -> CacheSignals:
    from ..exec.scancache import CACHE
    from ..serving.plancache import PLANS
    from ..serving.resultcache import RESULTS
    rstats = RESULTS.stats()
    return CacheSignals(
        scan_cache_resident_bytes=int(CACHE.resident_bytes),
        scan_cache_limit_bytes=int(CACHE.pool.limit),
        plan_cache_entries=len(PLANS),
        plan_cache_capacity=int(PLANS.capacity),
        result_cache_resident_bytes=int(rstats["resident_bytes"]),
        result_cache_limit_bytes=int(RESULTS.pool.limit))


def cluster_signals(now: Optional[float] = None) -> ClusterSignals:
    """Assemble one frozen :class:`ClusterSignals` snapshot.

    ``now`` (``time.time()`` domain) pins the windowed reads for
    deterministic tests; production callers omit it.
    """
    t = time.time() if now is None else float(now)
    return ClusterSignals(
        ts=t,
        groups=_group_signals(t),
        nodes=_node_signals(),
        caches=_cache_signals())

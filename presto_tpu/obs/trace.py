"""Lightweight span tracer with context propagation.

The role the reference spreads across QueryTracker/QueryStateMachine
timestamps and per-operator OperationTimer records, collapsed into one
span model: a span is a named [start, end) interval with a trace id, a
parent, and free-form attributes. Parentage flows through a contextvar,
so ``query -> plan -> operator -> device-sync/compile`` nests without
threading span handles through every call site; a span context can be
serialized into a task request (``Tracer.context``) and re-attached on a
worker (``Tracer.task_span``) so distributed traces stitch across the
wire by trace id.

Disabled (the default) the tracer must be invisible on hot paths:
``span()`` returns one shared no-op object and takes no lock; callers
wrapping per-batch work may additionally guard with ``TRACER.enabled``.
Finished spans land in a bounded ring; ``export()`` snapshots them and
``chrome_trace()`` renders the Chrome ``chrome://tracing`` / Perfetto
JSON format (one "X" complete event per span, processes keyed by node,
threads keyed by task/query).
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, Iterator, List, Optional

#: the active span for the current thread/context (parent of new spans)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "presto_tpu_span", default=None)

#: perf_counter -> epoch anchor: spans are timed with the monotonic
#: clock but exported on the wall clock so spans from different
#: processes line up on one Chrome-trace timeline
_EPOCH_WALL = time.time()
_EPOCH_PERF = time.perf_counter()


def _now() -> float:
    return _EPOCH_WALL + (time.perf_counter() - _EPOCH_PERF)


class Span:
    """One finished-or-running interval. Mutable while open; after
    ``end`` is set it is only read."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end", "node", "attrs", "_tracer", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: Dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = f"{tracer.node}.{next(tracer._seq)}"
        self.node = tracer.node
        self.attrs = attrs
        self.start = _now()
        self.end: Optional[float] = None
        self._token = None

    # -- context-manager protocol --------------------------------------------
    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None:
            self.attrs.setdefault("error", f"{type(exc).__name__}: {exc}")
        self.finish()
        return False

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def finish(self) -> None:
        if self.end is None:
            self.end = _now()
            self._tracer._record(self)

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "traceId": self.trace_id,
            "spanId": self.span_id, "parentId": self.parent_id,
            "node": self.node, "start": self.start,
            "end": self.end if self.end is not None else self.start,
            "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """Shared do-nothing span: the disabled tracer's only allocation."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def annotate(self, **attrs) -> None:
        pass

    def finish(self) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Process-wide span collector (one per process, ``TRACER``)."""

    def __init__(self, node: Optional[str] = None,
                 max_spans: int = 100_000):
        #: plain attribute (not a property) so hot paths pay one load
        self.enabled = os.environ.get("PRESTO_TPU_TRACE", "") \
            .strip().lower() not in ("", "0", "false", "off", "no")
        # random suffix: span ids must be globally unique across
        # processes for import_spans' dedup — containerized workers can
        # share a pid (every container's worker is pid 1)
        self.node = node or \
            f"pid-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._seq = itertools.count(1)
        self._ring: deque = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def enable(self, flag: bool = True) -> None:
        self.enabled = flag

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def _record(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span.to_dict())

    # -- span creation -------------------------------------------------------
    def span(self, name: str, **attrs):
        """New child span of the current context (or a new trace root).
        Returns the shared no-op when disabled."""
        if not self.enabled:
            return NOOP_SPAN
        parent = _CURRENT.get()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id, attrs)
        return Span(self, name, uuid.uuid4().hex[:16], None, attrs)

    def task_span(self, ctx: Optional[Dict], name: str, **attrs):
        """Span re-parented from a wire-carried context (a worker task
        resuming a coordinator trace). ``ctx`` is whatever ``context()``
        produced on the sending side; None/invalid degrades to a plain
        ``span()``."""
        if not self.enabled:
            return NOOP_SPAN
        if not isinstance(ctx, dict) or "traceId" not in ctx:
            return self.span(name, **attrs)
        return Span(self, name, str(ctx["traceId"]),
                    ctx.get("spanId"), attrs)

    def context(self) -> Optional[Dict]:
        """Wire-serializable context of the current span (ships inside
        task-create requests); None when disabled or outside a span."""
        if not self.enabled:
            return None
        cur = _CURRENT.get()
        if cur is None:
            return None
        return {"traceId": cur.trace_id, "spanId": cur.span_id}

    def wrap_iter(self, name: str, it: Iterator, **attrs) -> Iterator:
        """Span covering an iterator's lifetime (first ``next`` to
        exhaustion) — operator spans over streaming plan nodes. The
        parent is captured at call time, matching the plan structure
        rather than whichever operator happens to be draining."""
        if not self.enabled:
            return it
        parent = _CURRENT.get()
        trace_id = parent.trace_id if parent is not None \
            else uuid.uuid4().hex[:16]
        parent_id = parent.span_id if parent is not None else None

        def gen():
            span = Span(self, name, trace_id, parent_id, attrs)
            batches = 0
            try:
                for item in it:
                    batches += 1
                    yield item
            finally:
                span.attrs["batches"] = batches
                span.finish()
        return gen()

    # -- export / merge ------------------------------------------------------
    def export(self, trace_id: Optional[str] = None) -> List[Dict]:
        with self._lock:
            spans = list(self._ring)
        if trace_id is not None:
            spans = [s for s in spans if s["traceId"] == trace_id]
        return spans

    def import_spans(self, spans: List[Dict]) -> int:
        """Merge foreign (worker-exported) spans, deduplicating by span
        id — in-process workers share this ring with the coordinator, so
        a harvest must not double-record. Returns spans added."""
        if not spans:
            return 0
        with self._lock:
            seen = {s.get("spanId") for s in self._ring}
            added = 0
            for s in spans:
                if not isinstance(s, dict) or s.get("spanId") in seen:
                    continue
                seen.add(s.get("spanId"))
                self._ring.append(s)
                added += 1
            return added


#: the process-wide tracer
TRACER = Tracer()


def current_span_ids() -> Dict:
    """Correlation ids of the active span for structured logging
    (``obs.log``): ``query_id``/``task_id``/``stage_id`` attributes
    plus the trace id, when a span is open on this context."""
    cur = _CURRENT.get()
    if not isinstance(cur, Span):
        return {}
    out = {k: cur.attrs[k] for k in ("query_id", "task_id", "stage_id")
           if k in cur.attrs}
    out["trace_id"] = cur.trace_id
    return out


# -- Chrome-trace (chrome://tracing / Perfetto) export -----------------------

def chrome_trace(spans: List[Dict]) -> Dict:
    """Render exported spans as the Chrome Trace Event JSON object
    format: one complete ("X") event per span with microsecond
    timestamps, processes keyed by node, lanes (tids) keyed by
    task/query so concurrent work stacks readably, plus "M" metadata
    events naming both."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    events: List[Dict] = []

    def pid_of(node: str) -> int:
        if node not in pids:
            pids[node] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[node], "tid": 0,
                           "args": {"name": f"presto_tpu {node}"}})
        return pids[node]

    def tid_of(pid: int, lane: str) -> int:
        key = (pid, lane)
        if key not in tids:
            tids[key] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tids[key],
                           "args": {"name": lane}})
        return tids[key]

    for s in spans:
        attrs = s.get("attrs", {}) or {}
        pid = pid_of(s.get("node", "?"))
        lane = str(attrs.get("task_id") or attrs.get("query_id")
                   or s.get("traceId", "main"))
        start = float(s.get("start", 0.0))
        end = float(s.get("end", start))
        events.append({
            "ph": "X", "name": s.get("name", "?"), "cat": "presto_tpu",
            "ts": round(start * 1e6, 1),
            "dur": round(max(end - start, 0.0) * 1e6, 1),
            "pid": pid, "tid": tid_of(pid, lane),
            "args": {"traceId": s.get("traceId"),
                     "spanId": s.get("spanId"),
                     "parentId": s.get("parentId"), **attrs},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: List[Dict]) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return path

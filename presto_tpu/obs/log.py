"""Structured JSON-lines logging correlated by query/task ids.

The role of the reference's airlift log + QueryMonitor audit lines,
reshaped: one process-wide logger (``LOG``) that writes one JSON object
per line, stamping each record with the ``query_id``/``task_id``/
``trace_id`` of the active trace context (``obs.trace``) so engine log
lines join query traces without threading ids through every call site.

Off by default and free while off (one attribute load per call site).
Enable with ``LOG.configure(path=...)`` (append), ``stream=...`` (e.g.
``sys.stderr``), or the ``PRESTO_TPU_LOG`` environment variable
(``1``/``stderr`` or a file path). The CLI's ``--slow-query-log`` turns
it on for slow-query records.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import IO, Optional

from .trace import current_span_ids


class JsonLinesLogger:
    """Process-wide structured logger; one JSON object per line."""

    def __init__(self):
        self.enabled = False
        self._stream: Optional[IO] = None
        self._path: Optional[str] = None
        self._lock = threading.Lock()
        env = os.environ.get("PRESTO_TPU_LOG", "").strip()
        if env and env.lower() not in ("0", "false", "off", "no"):
            if env.lower() in ("1", "true", "on", "yes", "stderr"):
                self.configure(stream=sys.stderr)
            else:
                self.configure(path=env)

    def configure(self, path: Optional[str] = None,
                  stream: Optional[IO] = None) -> None:
        with self._lock:
            self._path = path
            self._stream = stream
            self.enabled = bool(path or stream)

    def close(self) -> None:
        self.configure()

    def log(self, event: str, **fields) -> None:
        """Emit one record; never raises (logging must not break
        queries). Trace-context ids are defaults — explicit kwargs
        win."""
        if not self.enabled:
            return
        doc = {"ts": round(time.time(), 6), "event": event}
        for k, v in current_span_ids().items():
            doc.setdefault(k, v)
        doc.update({k: v for k, v in fields.items() if v is not None})
        try:
            line = json.dumps(doc, default=str)
            with self._lock:
                if self._stream is not None:
                    self._stream.write(line + "\n")
                    self._stream.flush()
                elif self._path is not None:
                    with open(self._path, "a") as f:
                        f.write(line + "\n")
        except Exception:
            pass


#: the process-wide structured logger
LOG = JsonLinesLogger()

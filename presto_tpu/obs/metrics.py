"""Process-wide metrics registry: counters, gauges, histograms.

The role of the reference's JMX-exposed engine metrics (reference
presto-main/.../connector/jmx/ makes them queryable as SQL tables;
QueryManagerStats/SqlTaskManager counters feed them): named metrics
created on demand, updated from direct instrumentation (executor, spill
buffers, jit cache, exchange buffers, device scheduler) and from an
EventListenerManager sink (query/split completion), and surfaced as the
``system.runtime.metrics`` table.

Updates are deliberately tiny — one lock-guarded number update — so the
registry can stay always-on; nothing here touches the device.
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .._devtools.lockcheck import checked_lock

_INF = float("inf")

#: default histogram bucket upper bounds (seconds-flavoured exponential
#: ladder; the last implicit bucket is +Inf). Shared by every histogram
#: so the exposition endpoint can render Prometheus ``_bucket`` series
#: and ``snapshot()`` can derive p50/p95/p99 without a quantile store.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _quantile(q: float, count: int, bucket_counts: List[int],
              bounds: Tuple[float, ...], lo_clamp: float,
              hi_clamp: float) -> float:
    """Linear-interpolated quantile from bucket counts (the
    ``histogram_quantile`` estimate), clamped to the observed range."""
    target = q * count
    cum = 0
    lo = 0.0
    for i, c in enumerate(bucket_counts):
        hi = bounds[i] if i < len(bounds) else hi_clamp
        if c and cum + c >= target:
            frac = (target - cum) / c
            v = lo + (hi - lo) * frac
            return min(max(v, lo_clamp), hi_clamp)
        cum += c
        lo = hi
    return hi_clamp


class Counter:
    """Monotonic counter (``*_total`` names by convention)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write or high-water value."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def max_update(self, v: float) -> None:
        with self._lock:
            if v > self.value:
                self.value = float(v)


class Histogram:
    """Count/sum/min/max summary plus fixed exponential buckets, so the
    exposition endpoint can render Prometheus ``_bucket`` series and the
    SQL surface can carry derived p50/p95/p99."""

    __slots__ = ("name", "count", "sum", "min", "max", "buckets",
                 "bucket_counts", "_lock")

    def __init__(self, name: str,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = _INF
        self.max = -_INF
        self.buckets = tuple(buckets)
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self.bucket_counts[bisect.bisect_left(self.buckets, v)] += 1

    def state(self) -> Dict:
        """Consistent copy with cumulative buckets and derived
        quantiles — the shared feed of ``snapshot()`` and the
        Prometheus exposition.

        The quantiles are **process-lifetime** estimates (since-boot
        cumulative bucket counts): a recent latency spike dilutes into
        everything observed before it.  For "p95 over the last 5
        minutes" use the windowed series in ``obs.timeseries``
        (``TimeSeriesStore.window_quantile`` / the ``*_p95_5m``
        exposition gauges), which difference these cumulative buckets
        between samples."""
        with self._lock:
            count, total = self.count, self.sum
            mn, mx = self.min, self.max
            counts = list(self.bucket_counts)
        doc: Dict = {"name": self.name, "kind": "histogram",
                     "count": count, "sum": total}
        if count:
            doc["min"], doc["max"] = mn, mx
            doc["quantiles"] = {
                q: _quantile(q, count, counts, self.buckets, mn, mx)
                for q in (0.5, 0.95, 0.99)}
        cum = 0
        cumulative = []
        for i, c in enumerate(counts):
            cum += c
            le = self.buckets[i] if i < len(self.buckets) else _INF
            cumulative.append((le, cum))
        doc["buckets"] = cumulative
        return doc

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self.count:
                return None
            return _quantile(q, self.count, list(self.bucket_counts),
                             self.buckets, self.min, self.max)


class MetricsRegistry:
    """Name -> metric, created on first use; one per process
    (``REGISTRY``)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        # registry-level lock is order-validated under pytest; the hot
        # per-metric leaf locks (Counter/Gauge/Histogram) stay plain —
        # they never acquire anything else
        self._lock = checked_lock("metrics.registry")

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls(name)
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is {type(m).__name__}, "
                f"not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def collect(self) -> List[Dict]:
        """Typed metric states, one entry per metric — the feed of the
        Prometheus exposition (``obs.exposition``). Histograms stay
        structured (count/sum/buckets/quantiles); counters and gauges
        are ``{"name", "kind", "value"}``."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: List[Dict] = []
        for name, m in metrics:
            if isinstance(m, Counter):
                out.append({"name": name, "kind": "counter",
                            "value": m.value})
            elif isinstance(m, Gauge):
                out.append({"name": name, "kind": "gauge",
                            "value": m.value})
            elif isinstance(m, Histogram):
                out.append(m.state())
        return out

    def value(self, name: str, default: float = 0.0) -> float:
        """One family's scalar from the snapshot — the convenience
        tests/benches use to watch a single counter move."""
        for m in self.snapshot():
            if m["name"] == name:
                return float(m["value"])
        return default

    def snapshot(self) -> List[Dict]:
        """JSON-able rows, one per scalar: histograms flatten to
        ``name.count/sum/min/max/p50/p95/p99`` — the
        ``system.runtime.metrics`` surface."""
        out: List[Dict] = []
        for m in self.collect():
            if m["kind"] != "histogram":
                out.append(m)
                continue
            name = m["name"]
            out.append({"name": f"{name}.count", "kind": "histogram",
                        "value": float(m["count"])})
            out.append({"name": f"{name}.sum", "kind": "histogram",
                        "value": m["sum"]})
            if m["count"]:
                out.append({"name": f"{name}.min",
                            "kind": "histogram", "value": m["min"]})
                out.append({"name": f"{name}.max",
                            "kind": "histogram", "value": m["max"]})
                for q, label in ((0.5, "p50"), (0.95, "p95"),
                                 (0.99, "p99")):
                    out.append({"name": f"{name}.{label}",
                                "kind": "histogram",
                                "value": m["quantiles"][q]})
        return out

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests). Instrumentation sites
        cache metric objects at module import (spill/taskexec/worker),
        so clearing the dict would orphan those references — values
        reset, identities survive."""
        with self._lock:
            for m in self._metrics.values():
                if isinstance(m, (Counter, Gauge)):
                    m.value = 0.0
                elif isinstance(m, Histogram):
                    m.count, m.sum = 0, 0.0
                    m.min, m.max = _INF, -_INF
                    m.bucket_counts = [0] * (len(m.buckets) + 1)


#: the process-wide registry
REGISTRY = MetricsRegistry()


# -- task registry (system.runtime.tasks) ------------------------------------

_TERMINAL_TASK_STATES = ("FINISHED", "FAILED", "ABORTED")


class TaskRegistry:
    """Bounded registry of worker-task states: the feed of the
    ``system.runtime.tasks`` table (reference SqlTaskManager's task
    info map behind server/TaskResource.java)."""

    def __init__(self, max_tasks: int = 1000):
        self._tasks: "OrderedDict[str, Dict]" = OrderedDict()
        self._max = max_tasks
        self._lock = checked_lock("metrics.tasks")

    def update(self, task_id: str, **fields) -> None:
        evicted = 0
        with self._lock:
            t = self._tasks.get(task_id)
            if t is None:
                t = self._tasks[task_id] = {
                    "task_id": task_id, "created": time.time()}
            t.update(fields)
            # over the cap: evict the oldest terminal task first — a
            # RUNNING entry must stay visible even when the registry is
            # full of history; only when everything is live does the
            # plain-oldest fall (and the counter makes either loss
            # observable instead of silent)
            while len(self._tasks) > self._max:
                victim = next(
                    (k for k, v in self._tasks.items()
                     if v.get("state") in _TERMINAL_TASK_STATES), None)
                if victim is None:
                    victim = next(iter(self._tasks))
                del self._tasks[victim]
                evicted += 1
        if evicted:
            REGISTRY.counter("task_registry_evicted_total").inc(evicted)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [dict(t) for t in self._tasks.values()]

    def reset(self) -> None:
        with self._lock:
            self._tasks.clear()


TASKS = TaskRegistry()


# -- node registry (system.runtime.nodes) -------------------------------------

class NodeRegistry:
    """Coordinator-side view of cluster nodes: the feed of the
    ``system.runtime.nodes`` table and of the node-labeled series on the
    coordinator's ``/v1/metrics`` exposition (reference
    connector/system/NodesSystemTable over DiscoveryNodeManager).
    Updated by the ClusterRunner's heartbeat/info polls; heartbeat age
    is computed at read time so a stalled poller shows as a growing
    age, not a frozen-fresh one."""

    def __init__(self):
        self._nodes: Dict[str, Dict] = {}
        self._lock = checked_lock("metrics.nodes")

    def update(self, node_id: str, seen: bool = True, drop=(),
               **fields) -> None:
        """Merge ``fields`` into the node's doc; ``drop`` removes keys a
        fresh heartbeat no longer carries (a merge-only update would
        latch e.g. HBM gauges from a node's previous incarnation)."""
        with self._lock:
            n = self._nodes.setdefault(node_id, {"node_id": node_id})
            n.update(fields)
            for k in drop:
                n.pop(k, None)
            if seen:
                n["last_seen"] = time.monotonic()

    def snapshot(self, now: Optional[float] = None) -> List[Dict]:
        """Node docs with ``heartbeat_age_s`` derived from ONE clock
        read — callers rendering several surfaces in one poll pass
        ``now`` (``time.monotonic()``) so every row and every surface
        agree on the same instant instead of drifting per-row."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            out = []
            for n in self._nodes.values():
                doc = dict(n)
                seen = doc.pop("last_seen", None)
                doc["heartbeat_age_s"] = (
                    round(now - seen, 3) if seen is not None else _INF)
                out.append(doc)
            return out

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()


NODES = NodeRegistry()


# -- EventListenerManager sink -----------------------------------------------

def attach_event_listeners(events,
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """Register metrics-feeding listeners on an EventListenerManager:
    query-completion state counters + latency histogram, split
    completion counters — the sink half of the metrics story (the other
    half is direct instrumentation)."""
    reg = registry if registry is not None else REGISTRY

    def on_query_completed(ev) -> None:
        state = str(getattr(ev, "state", "unknown")).lower()
        reg.counter(f"queries_{state}_total").inc()
        reg.histogram("query_seconds").observe(
            getattr(ev, "elapsed_ms", 0.0) / 1e3)

    def on_split_completed(ev) -> None:
        reg.counter("splits_completed_total").inc()
        reg.counter("split_batches_total").inc(
            getattr(ev, "batches", 0) or 0)
        reg.histogram("split_seconds").observe(
            (getattr(ev, "wall_ms", 0.0) or 0.0) / 1e3)

    events.register(on_query_completed)
    events.register_split_listener(on_split_completed)

"""Process-wide metrics registry: counters, gauges, histograms.

The role of the reference's JMX-exposed engine metrics (reference
presto-main/.../connector/jmx/ makes them queryable as SQL tables;
QueryManagerStats/SqlTaskManager counters feed them): named metrics
created on demand, updated from direct instrumentation (executor, spill
buffers, jit cache, exchange buffers, device scheduler) and from an
EventListenerManager sink (query/split completion), and surfaced as the
``system.runtime.metrics`` table.

Updates are deliberately tiny — one lock-guarded number update — so the
registry can stay always-on; nothing here touches the device.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

_INF = float("inf")


class Counter:
    """Monotonic counter (``*_total`` names by convention)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write or high-water value."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def max_update(self, v: float) -> None:
        with self._lock:
            if v > self.value:
                self.value = float(v)


class Histogram:
    """Count/sum/min/max summary (no buckets: the consumers are SQL and
    EXPLAIN output, not a quantile store)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = _INF
        self.max = -_INF
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v


class MetricsRegistry:
    """Name -> metric, created on first use; one per process
    (``REGISTRY``)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls(name)
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is {type(m).__name__}, "
                f"not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> List[Dict]:
        """JSON-able rows, one per scalar: histograms flatten to
        ``name.count/sum/min/max`` — the ``system.runtime.metrics``
        surface."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: List[Dict] = []
        for name, m in metrics:
            if isinstance(m, Counter):
                out.append({"name": name, "kind": "counter",
                            "value": m.value})
            elif isinstance(m, Gauge):
                out.append({"name": name, "kind": "gauge",
                            "value": m.value})
            elif isinstance(m, Histogram):
                out.append({"name": f"{name}.count", "kind": "histogram",
                            "value": float(m.count)})
                out.append({"name": f"{name}.sum", "kind": "histogram",
                            "value": m.sum})
                if m.count:
                    out.append({"name": f"{name}.min",
                                "kind": "histogram", "value": m.min})
                    out.append({"name": f"{name}.max",
                                "kind": "histogram", "value": m.max})
        return out

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests). Instrumentation sites
        cache metric objects at module import (spill/taskexec/worker),
        so clearing the dict would orphan those references — values
        reset, identities survive."""
        with self._lock:
            for m in self._metrics.values():
                if isinstance(m, (Counter, Gauge)):
                    m.value = 0.0
                elif isinstance(m, Histogram):
                    m.count, m.sum = 0, 0.0
                    m.min, m.max = _INF, -_INF


#: the process-wide registry
REGISTRY = MetricsRegistry()


# -- task registry (system.runtime.tasks) ------------------------------------

class TaskRegistry:
    """Bounded registry of worker-task states: the feed of the
    ``system.runtime.tasks`` table (reference SqlTaskManager's task
    info map behind server/TaskResource.java)."""

    def __init__(self, max_tasks: int = 1000):
        self._tasks: "OrderedDict[str, Dict]" = OrderedDict()
        self._max = max_tasks
        self._lock = threading.Lock()

    def update(self, task_id: str, **fields) -> None:
        with self._lock:
            t = self._tasks.get(task_id)
            if t is None:
                t = self._tasks[task_id] = {
                    "task_id": task_id, "created": time.time()}
                while len(self._tasks) > self._max:
                    self._tasks.popitem(last=False)
            t.update(fields)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [dict(t) for t in self._tasks.values()]

    def reset(self) -> None:
        with self._lock:
            self._tasks.clear()


TASKS = TaskRegistry()


# -- EventListenerManager sink -----------------------------------------------

def attach_event_listeners(events,
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """Register metrics-feeding listeners on an EventListenerManager:
    query-completion state counters + latency histogram, split
    completion counters — the sink half of the metrics story (the other
    half is direct instrumentation)."""
    reg = registry if registry is not None else REGISTRY

    def on_query_completed(ev) -> None:
        state = str(getattr(ev, "state", "unknown")).lower()
        reg.counter(f"queries_{state}_total").inc()
        reg.histogram("query_seconds").observe(
            getattr(ev, "elapsed_ms", 0.0) / 1e3)

    def on_split_completed(ev) -> None:
        reg.counter("splits_completed_total").inc()
        reg.counter("split_batches_total").inc(
            getattr(ev, "batches", 0) or 0)
        reg.histogram("split_seconds").observe(
            (getattr(ev, "wall_ms", 0.0) or 0.0) / 1e3)

    events.register(on_query_completed)
    events.register_split_listener(on_split_completed)

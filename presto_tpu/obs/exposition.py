"""Prometheus / OpenMetrics text exposition of the metrics registry.

The scrape surface the reference leaves to its JMX exporter agents:
``GET /v1/metrics`` on workers (``server/worker.py``) and on the
coordinator protocol server (``server/protocol.py``) renders
``MetricsRegistry.collect()`` in the Prometheus text format —
``# TYPE`` lines, counter/gauge samples, histogram ``_bucket``/
``_sum``/``_count`` series plus derived ``_quantile`` gauges — ending
with the OpenMetrics ``# EOF`` marker.

The engine's dotted metric names (``operator_batches_total.tablescan``)
become labeled series (``operator_batches_total{key="tablescan"}``),
and the coordinator passes its ``NodeRegistry`` so per-node series
(``node_heartbeat_age_seconds{node="worker-1"}``) are re-published from
one federating scrape endpoint.

``parse_exposition`` is the matching tiny parser: tests round-trip the
rendered text through it, and it is enough to point a real Prometheus
at the endpoint and get the same numbers.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry, NodeRegistry

#: Prometheus metric-name charset; anything else is collapsed to "_"
_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _name(raw: str) -> str:
    return _NAME_OK.sub("_", raw)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Family:
    def __init__(self, kind: str):
        self.kind = kind
        self.samples: List[Tuple[str, Dict[str, str], float]] = []


def render_exposition(registry: Optional[MetricsRegistry] = None,
                      nodes: Optional[NodeRegistry] = None) -> str:
    """Registry (and optionally node-registry) state as Prometheus text
    exposition. Deterministic ordering: families sorted by name."""
    reg = registry if registry is not None else REGISTRY
    fams: Dict[str, _Family] = {}

    def family(name: str, kind: str) -> _Family:
        f = fams.get(name)
        if f is None:
            f = fams[name] = _Family(kind)
        return f

    for m in reg.collect():
        base, _, sub = m["name"].partition(".")
        base = _name(base)
        labels = {"key": sub} if sub else {}
        if m["kind"] in ("counter", "gauge"):
            family(base, m["kind"]).samples.append(
                (base, labels, float(m["value"])))
            continue
        # histogram: cumulative buckets + sum/count, then quantile
        # gauges derived from the same buckets (p50/p95/p99)
        f = family(base, "histogram")
        for le, cum in m["buckets"]:
            f.samples.append((f"{base}_bucket",
                              {**labels, "le": _fmt(le)}, float(cum)))
        f.samples.append((f"{base}_sum", labels, float(m["sum"])))
        f.samples.append((f"{base}_count", labels, float(m["count"])))
        for q, v in sorted((m.get("quantiles") or {}).items()):
            family(f"{base}_quantile", "gauge").samples.append(
                (f"{base}_quantile",
                 {**labels, "quantile": _fmt(q)}, float(v)))

    # windowed quantile gauges from the time-series store
    # (obs/timeseries.py): the ``_quantile`` gauges above are
    # process-LIFETIME estimates (kept for back-compat); these
    # ``*_p95_5m``-style series difference the cumulative buckets
    # between samples, so they mean "over the last 5 minutes".
    # Absent until the sampler has two points in the window.
    from .timeseries import TIMESERIES
    for series in (TIMESERIES.series_names()
                   if reg is TIMESERIES.registry else ()):
        if TIMESERIES.kind(series) != "histogram":
            continue
        base, _, sub = series.partition(".")
        base = _name(base)
        labels = {"key": sub} if sub else {}
        for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = TIMESERIES.window_quantile(series, 300.0, q)
            if v is None:
                continue
            fam_name = f"{base}_{tag}_5m"
            family(fam_name, "gauge").samples.append(
                (fam_name, labels, float(v)))

    if nodes is not None:
        for n in nodes.snapshot():
            lab = {"node": str(n.get("node_id", ""))}
            family("node_up", "gauge").samples.append(
                ("node_up", lab,
                 1.0 if n.get("state") == "ACTIVE" else 0.0))
            family("node_heartbeat_age_seconds", "gauge").samples.append(
                ("node_heartbeat_age_seconds", lab,
                 float(n.get("heartbeat_age_s", math.inf))))
            family("node_active_tasks", "gauge").samples.append(
                ("node_active_tasks", lab,
                 float(n.get("active_tasks", 0) or 0)))
            family("node_mem_pool_peak_bytes", "gauge").samples.append(
                ("node_mem_pool_peak_bytes", lab,
                 float(n.get("mem_pool_peak_bytes", 0) or 0)))
            # HBM telemetry federated from worker heartbeats
            # (device.memory_stats() sums; absent on nodes that never
            # reported one, so a CPU-only cluster adds no noise)
            if n.get("hbm_in_use_bytes") is not None:
                family("node_hbm_in_use_bytes", "gauge").samples.append(
                    ("node_hbm_in_use_bytes", lab,
                     float(n.get("hbm_in_use_bytes") or 0)))
                family("node_hbm_peak_bytes", "gauge").samples.append(
                    ("node_hbm_peak_bytes", lab,
                     float(n.get("hbm_peak_bytes") or 0)))

    lines: List[str] = []
    for name in sorted(fams):
        f = fams[name]
        lines.append(f"# TYPE {name} {f.kind}")
        for sample, labels, value in f.samples:
            lines.append(f"{sample}{_labels(labels)} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_exposition(text: str):
    """Parse Prometheus text exposition back into
    ``(samples, types)``: ``samples`` maps
    ``(sample_name, ((label, value), ...))`` to a float, ``types`` maps
    family name to its declared type. Raises ValueError on malformed
    lines — the round-trip test is a format validator."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: bad sample {line!r}")
        name, labelstr, value = m.groups()
        labels = []
        if labelstr:
            matched = _LABEL.findall(labelstr)
            stripped = _LABEL.sub("", labelstr).replace(",", "").strip()
            if stripped:
                raise ValueError(
                    f"line {lineno}: bad labels {labelstr!r}")
            labels = [(k, v.replace('\\"', '"').replace("\\n", "\n")
                       .replace("\\\\", "\\")) for k, v in matched]
        samples[(name, tuple(sorted(labels)))] = float(value)
    return samples, types

"""Per-resource-group SLOs: error budgets, multi-window burn rates, and
an OK→WARN→PAGE alert state machine with hysteresis.

Objectives are declared on serving resource groups
(``etc/resource-groups.json``, parsed by ``server/resource_groups.py``)::

    {"name": "dash", "hardConcurrencyLimit": 4,
     "slo": {"latencyTargetMs": 500, "latencyObjective": 0.95,
             "availabilityObjective": 0.999, "windows": [300, 3600]}}

reads "95% of dash queries finish under 500 ms, 99.9% succeed".  The
tracker re-reads the live group tree on every evaluation (weak manager
registry in ``serving/groups.py``), so objectives follow whatever
server(s) the process is running — no registration dance.

The math is the Google SRE multi-window burn-rate recipe:

- error fraction over a trailing window comes from the time-series
  store (``obs/timeseries.py``): latency objectives difference the
  cumulative bucket counts of ``serving_latency_seconds.<group>`` and
  count observations over the threshold as errors; availability
  objectives difference ``serving_errors_total.<group>`` against
  ``serving_requests_total.<group>``;
- ``burn = error_fraction / (1 - objective)`` — burn 1.0 spends the
  budget exactly at the sustainable rate, burn 10 spends a 30-day
  budget in 3 days;
- an alert escalates only when **every** window burns (short window =
  fast detection, long window = noise floor): ``min(burns) >=
  PAGE_ENTER_BURN`` pages, ``>= WARN_ENTER_BURN`` warns;
- hysteresis on the way down: the state steps down only after the burn
  stays below ``EXIT_FRACTION`` of the current state's entry threshold
  for ``CLEAR_AFTER`` consecutive evaluations — a series hovering on
  the boundary cannot flap.

Transitions land in a bounded alert log (``system.runtime.alerts``),
current state in ``system.runtime.slo``, and the registry grows
``slo_burn_rate_ratio`` / ``slo_error_budget_remaining_ratio`` gauges
plus ``slo_alert_transitions_total``.  Latency thresholds snap **up**
to the histogram bucket ladder (``obs.metrics.DEFAULT_BUCKETS``), so
pick thresholds on bucket bounds for exact semantics.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .._devtools.lockcheck import checked_lock
from .metrics import REGISTRY
from .timeseries import TIMESERIES, TimeSeriesStore

#: Alert rule registry: every alert the tracker can raise, by name.
#: ``tools/analyze`` validates that each rule referenced in code (via
#: :func:`alert_rule`) is declared here and documented in
#: docs/observability.md — unknown or undocumented names are findings.
ALERT_RULES: Dict[str, str] = {
    "latency_burn": ("multi-window burn of a latency objective: too "
                     "many queries over the group's latency threshold"),
    "availability_burn": ("multi-window burn of an availability "
                          "objective: too many failed queries"),
}


def alert_rule(name: str) -> str:
    """Validate ``name`` against :data:`ALERT_RULES` and return it."""
    if name not in ALERT_RULES:
        raise ValueError(f"unknown alert rule {name!r}; "
                         f"declared: {sorted(ALERT_RULES)}")
    return name


DEFAULT_WINDOWS: Tuple[float, float] = (300.0, 3600.0)  # 5m + 1h
WARN_ENTER_BURN = 2.0
PAGE_ENTER_BURN = 10.0
EXIT_FRACTION = 0.5     # step down below half the entry threshold...
CLEAR_AFTER = 2         # ...held for this many consecutive evaluations

_RANK = {"OK": 0, "WARN": 1, "PAGE": 2}


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective on one resource group."""
    group: str                  # dotted group path, e.g. "serving.dash"
    objective: str              # "latency" | "availability"
    target: float               # good fraction, e.g. 0.95
    threshold_s: Optional[float] = None   # latency objectives only
    windows: Tuple[float, ...] = DEFAULT_WINDOWS

    @property
    def rule(self) -> str:
        if self.objective == "latency":
            return alert_rule("latency_burn")
        return alert_rule("availability_burn")

    @property
    def key(self) -> Tuple[str, str]:
        return (self.group, self.objective)


def burn_rate(error_fraction: float, target: float) -> float:
    """``error_fraction / (1 - target)`` — 1.0 spends the budget exactly
    at the sustainable rate."""
    allowed = max(1e-9, 1.0 - float(target))
    return max(0.0, float(error_fraction)) / allowed


def objectives_from_spec(group_path: str,
                         spec: Optional[dict]) -> List[SloObjective]:
    """Parse one group's normalized ``slo`` block into objectives."""
    if not spec:
        return []
    windows = tuple(float(w) for w in spec.get("windows",
                                               DEFAULT_WINDOWS))
    if len(windows) < 1:
        windows = DEFAULT_WINDOWS
    out: List[SloObjective] = []
    if spec.get("latencyObjective") is not None:
        thr_ms = spec.get("latencyTargetMs")
        if thr_ms is None:
            raise ValueError(
                f"group {group_path!r}: latencyObjective requires "
                "latencyTargetMs")
        out.append(SloObjective(group_path, "latency",
                                float(spec["latencyObjective"]),
                                threshold_s=float(thr_ms) / 1000.0,
                                windows=windows))
    if spec.get("availabilityObjective") is not None:
        out.append(SloObjective(group_path, "availability",
                                float(spec["availabilityObjective"]),
                                windows=windows))
    return out


class _AlertState:
    __slots__ = ("state", "since", "ok_streak")

    def __init__(self, now: float) -> None:
        self.state = "OK"
        self.since = now
        self.ok_streak = 0


class SloTracker:
    """Evaluates every declared objective against the time-series store.

    Driven by the store's sampler listener hook in production
    (:meth:`install`); tests call :meth:`evaluate` with explicit
    timestamps for deterministic time.
    """

    ALERT_LOG_POINTS = 256
    HISTORY_POINTS = 512

    def __init__(self, store: Optional[TimeSeriesStore] = None) -> None:
        self._store = store if store is not None else TIMESERIES
        self._lock = checked_lock("slo.tracker")
        self._states: Dict[Tuple[str, str], _AlertState] = {}
        self._alerts: deque = deque(maxlen=self.ALERT_LOG_POINTS)
        self._history: deque = deque(maxlen=self.HISTORY_POINTS)

    def install(self) -> None:
        """Hook :meth:`evaluate` after every sampler tick (idempotent)."""
        self._store.add_listener(self.evaluate)

    # -- objective discovery ------------------------------------------------

    def objectives(self) -> List[SloObjective]:
        """Objectives of every live manager's group tree, deduplicated
        by (group path, objective kind) — first manager wins."""
        from ..serving.groups import live_managers
        out: List[SloObjective] = []
        seen = set()
        for mgr in live_managers():
            stack = list(mgr.info())
            while stack:
                g = stack.pop()
                for obj in objectives_from_spec(g["id"], g.get("slo")):
                    if obj.key not in seen:
                        seen.add(obj.key)
                        out.append(obj)
                stack.extend(g["subGroups"])
        out.sort(key=lambda o: o.key)
        return out

    # -- burn math ----------------------------------------------------------

    def _error_fraction(self, obj: SloObjective, window: float,
                        now: float) -> Optional[float]:
        """Fraction of bad events over the trailing window, or ``None``
        when the window saw no traffic (no burn without evidence)."""
        if obj.objective == "latency":
            delta = self._store.window_counts(
                f"serving_latency_seconds.{obj.group}", window, now=now)
            if delta is None:
                return None
            count, _total, cum_counts, bounds = delta
            if count <= 0:
                return None
            # good = observations at or under the threshold, read off
            # the cumulative window delta at the first bound >= the
            # threshold (thresholds snap UP to the bucket ladder)
            good = count
            for i, bound in enumerate(bounds):
                if bound >= obj.threshold_s:
                    good = cum_counts[i]
                    break
            else:
                return 0.0  # threshold above the ladder: all good
            return (count - good) / count
        req = self._store.rate(f"serving_requests_total.{obj.group}",
                               window, now=now)
        err = self._store.rate(f"serving_errors_total.{obj.group}",
                               window, now=now)
        if req is None or req <= 0:
            return None
        return min(1.0, max(0.0, (err or 0.0) / req))

    def burns(self, obj: SloObjective,
              now: Optional[float] = None) -> Dict[float, Optional[float]]:
        """Burn rate per window; ``None`` where the window has no data."""
        t = time.time() if now is None else float(now)
        out: Dict[float, Optional[float]] = {}
        for w in obj.windows:
            frac = self._error_fraction(obj, w, t)
            out[w] = None if frac is None else burn_rate(frac,
                                                         obj.target)
        return out

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over every declared objective.

        Returns the alert-log entries appended by this pass (normally
        empty).  Gauges and the history ring update every pass.
        """
        t = time.time() if now is None else float(now)
        transitions: List[dict] = []
        for obj in self.objectives():
            burns = self.burns(obj, now=t)
            known = [b for b in burns.values() if b is not None]
            # escalate only when EVERY window burns; windows with no
            # data hold the alert down (no page without evidence)
            min_burn = min(known) if len(known) == len(burns) else 0.0
            long_w = max(obj.windows)
            long_burn = burns.get(long_w)
            budget = max(0.0, 1.0 - long_burn) if long_burn is not None \
                else 1.0
            label = f"{obj.group}:{obj.objective}"
            for w, b in burns.items():
                REGISTRY.gauge(
                    f"slo_burn_rate_ratio.{label}:{int(w)}s").set(
                        b if b is not None else 0.0)
            REGISTRY.gauge(
                f"slo_error_budget_remaining_ratio.{label}").set(budget)
            with self._lock:
                st = self._states.get(obj.key)
                if st is None:
                    st = self._states[obj.key] = _AlertState(t)
                new_state = self._step(st, min_burn)
                if new_state != st.state:
                    entry = {
                        "ts": t, "group": obj.group,
                        "objective": obj.objective, "rule": obj.rule,
                        "from": st.state, "to": new_state,
                        "burn": {str(int(w)): b
                                 for w, b in burns.items()},
                    }
                    self._alerts.append(entry)
                    transitions.append(entry)
                    st.state = new_state
                    st.since = t
                    st.ok_streak = 0
                    REGISTRY.counter(
                        f"slo_alert_transitions_total.{label}").inc()
                point = {"t": t, "group": obj.group,
                         "objective": obj.objective,
                         "burn": {str(int(w)): b
                                  for w, b in burns.items()},
                         "state": st.state}
                if obj.objective == "latency":
                    p95 = self._store.window_quantile(
                        f"serving_latency_seconds.{obj.group}",
                        min(obj.windows), 0.95, now=t)
                    point["p95_ms"] = (p95 * 1000.0
                                       if p95 is not None else None)
                self._history.append(point)
        return transitions

    @staticmethod
    def _step(st: _AlertState, min_burn: float) -> str:
        """State-machine step: immediate escalation, hysteretic decay."""
        desired = ("PAGE" if min_burn >= PAGE_ENTER_BURN else
                   "WARN" if min_burn >= WARN_ENTER_BURN else "OK")
        if _RANK[desired] > _RANK[st.state]:
            return desired
        if _RANK[desired] < _RANK[st.state]:
            entry = (PAGE_ENTER_BURN if st.state == "PAGE"
                     else WARN_ENTER_BURN)
            if min_burn < entry * EXIT_FRACTION:
                st.ok_streak += 1
                if st.ok_streak >= CLEAR_AFTER:
                    return desired
            else:
                st.ok_streak = 0
        else:
            st.ok_streak = 0
        return st.state

    # -- read surfaces ------------------------------------------------------

    def state_of(self, group: str, objective: str) -> str:
        with self._lock:
            st = self._states.get((group, objective))
            return st.state if st is not None else "OK"

    def snapshot_rows(self, now: Optional[float] = None) -> List[Tuple]:
        """``system.runtime.slo`` rows: one per objective."""
        t = time.time() if now is None else float(now)
        rows: List[Tuple] = []
        for obj in self.objectives():
            burns = self.burns(obj, now=t)
            short_w, long_w = min(obj.windows), max(obj.windows)
            long_burn = burns.get(long_w)
            budget = max(0.0, 1.0 - long_burn) if long_burn is not None \
                else 1.0
            with self._lock:
                st = self._states.get(obj.key)
                state = st.state if st is not None else "OK"
                since = st.since if st is not None else None
            rows.append((
                obj.group, obj.objective, obj.rule, obj.target,
                obj.threshold_s * 1000.0 if obj.threshold_s is not None
                else None,
                state, since,
                burns.get(short_w), long_burn, budget))
        return rows

    def alert_rows(self) -> List[Tuple]:
        """``system.runtime.alerts`` rows, oldest first."""
        with self._lock:
            entries = list(self._alerts)
        rows = []
        for e in entries:
            burns = e["burn"]
            keys = sorted(burns, key=float)
            short = burns[keys[0]] if keys else None
            long_ = burns[keys[-1]] if keys else None
            rows.append((e["ts"], e["group"], e["objective"], e["rule"],
                         e["from"], e["to"], short, long_))
        return rows

    def alert_log(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._alerts]

    def history(self) -> List[dict]:
        """Per-evaluation burn/p95 timeline (bench ``slo`` block feed)."""
        with self._lock:
            return [dict(e) for e in self._history]

    def reset(self) -> None:
        with self._lock:
            self._states.clear()
            self._alerts.clear()
            self._history.clear()


SLO = SloTracker()


def slo_block(store: Optional[TimeSeriesStore] = None,
              tracker: Optional[SloTracker] = None,
              max_timeline_points: int = 240) -> dict:
    """The serving ``slo`` block: declared objectives with final
    burn/budget/state, every alert transition, and the per-evaluation
    burn timeline (windowed p95 alongside, for latency objectives).

    One builder serves both consumers — ``bench.py serving`` pins it
    into SERVING_r*.json and the coordinator serves it live on
    ``GET /v1/slo`` (the fleet bench merges one block per coordinator).
    Schema is owned by tools/slo_report.py — check_bench_regression
    --kind serving validates every pin through it."""
    store = store if store is not None else TIMESERIES
    tracker = tracker if tracker is not None else SLO
    tracker.evaluate()  # flush a final point so the timeline ends "now"
    objectives = []
    for (group, objective, rule, target, threshold_ms, state, _since,
         burn_short, burn_long, budget) in tracker.snapshot_rows():
        objectives.append({
            "group": group, "objective": objective, "rule": rule,
            "target": target, "threshold_ms": threshold_ms,
            "state": state,
            "burn_short": burn_short and round(burn_short, 4),
            "burn_long": burn_long and round(burn_long, 4),
            "budget_remaining": round(budget, 4)})
    alerts = [{"ts": round(e["ts"], 3), "group": e["group"],
               "objective": e["objective"], "rule": e["rule"],
               "from": e["from"], "to": e["to"]}
              for e in tracker.alert_log()]
    timeline = []
    for e in tracker.history():
        burns = [b for b in e["burn"].values() if b is not None]
        pt = {"t": round(e["t"], 3), "group": e["group"],
              "objective": e["objective"],
              "burn": round(max(burns), 4) if burns else None,
              "state": e["state"]}
        if e.get("p95_ms") is not None:
            pt["p95_ms"] = round(e["p95_ms"], 2)
        timeline.append(pt)
    # keep the pin readable: stride the timeline down, always keeping
    # the final point of each objective
    if len(timeline) > max_timeline_points:
        stride = ((len(timeline) + max_timeline_points - 1)
                  // max_timeline_points)
        tail = timeline[-len(objectives):] if objectives else []
        timeline = [p for i, p in enumerate(timeline)
                    if i % stride == 0 or p in tail]
    return {"sample_interval_s": store.sample_interval_s,
            "objectives": objectives, "alerts": alerts,
            "timeline": timeline}

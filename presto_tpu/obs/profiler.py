"""Device profiling and cost attribution.

The role of the reference's OperatorStats device accounting ("Presto on
GPUs" motivates operator-level accelerator time; tf.data's
input-bound-vs-compute-bound framing is the verdict we surface): host
wall times lie on an async-dispatch backend, so this module holds the
engine's *device-level* truth:

- ``EXECUTABLES`` — one record per compiled jit entry (``ops/jitcache``
  and the fused-chain pipelines): compile seconds, invocation count,
  cumulative *device* time, and lazy XLA introspection
  (``lowered.cost_analysis()`` FLOPs / bytes-accessed,
  ``compiled.memory_analysis()`` arg/output/temp bytes). Surfaced as
  the ``system.runtime.executables`` table and the EXPLAIN ANALYZE
  "Executables" section.
- a **profile context** (``profiled()``): while active, every cached
  jit dispatch is bracketed with ``jax.block_until_ready`` so the
  measured interval is device time, and attributed to the plan operator
  whose iterator frame made the call (``operator_scope``, set by
  ``exec/stats.StatsCollector.wrap``). Off (the default) the only cost
  per dispatch is one contextvar load and an int increment; an optional
  process-wide ``EXECUTABLES.sample_every`` times every Nth call for
  always-on sampling.
- **HBM telemetry** (``sample_hbm``): ``device.memory_stats()`` gauges,
  sampled on worker heartbeats and by the local
  ``system.runtime.nodes`` fallback.
- **device-trace merging** (``merge_profile_dir``): folds the Chrome
  trace ``jax.profiler.trace`` wrote (XLA device tracks) into the span
  tracer's Chrome-trace export so host spans and device kernels land on
  one Perfetto timeline (the CLI's ``--profile-out``).

Caveat worth stating once: bracketing with ``block_until_ready``
serializes the dispatch pipeline — profile mode trades overlap for
truth. That is why it is a per-query session property (``profile``),
auto-enabled under EXPLAIN ANALYZE (which already pays per-batch syncs
for row counts), and never on for plain queries.
"""
from __future__ import annotations

import contextlib
import contextvars
import glob
import gzip
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .metrics import REGISTRY

#: active profile session (None = off) — checked on every jit dispatch
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "presto_tpu_profile", default=None)

#: (stats_collector, plan_node) of the operator whose iterator frame is
#: currently executing — innermost wins, set by StatsCollector.wrap
_OP: contextvars.ContextVar = contextvars.ContextVar(
    "presto_tpu_operator", default=None)

_DEVICE_SECONDS = REGISTRY.counter("jit_cache_device_seconds_total")
#: every cached-entry dispatch (incremented by ops/jitcache on the hot
#: path — one lock-guarded add, the registry's standard cost)
INVOCATIONS = REGISTRY.counter("jit_cache_invocations_total")


class ExecutableRecord:
    """One cached jit entry's ledger. Cheap fields (compile seconds,
    invocations, device seconds) are filled on the hot path; XLA
    introspection is computed lazily from the first call's avals so a
    query never pays a second compile unless someone asks."""

    __slots__ = ("name", "static_key", "compiles", "compile_seconds",
                 "invocations", "device_time_s", "created_at", "evicted",
                 "_key_repr", "_fn", "_avals", "_analysis", "_lock",
                 "_alock")

    def __init__(self, name: str, static_key: str):
        self.name = name
        self.static_key = static_key
        self.compiles = 0
        self.compile_seconds = 0.0
        self.invocations = 0
        self.device_time_s = 0.0
        self.created_at = time.time()
        # set when the registry's leak-guard cap drops this record; the
        # owning _TimedEntry keeps dispatching into it, so the next
        # dispatch readmits it (counts survive, nothing goes invisible)
        self.evicted = False
        self._key_repr = static_key
        self._fn = None
        self._avals = None
        self._analysis: Optional[Dict] = None
        # counter lock, held for nanoseconds on the dispatch path;
        # analysis gets its own lock because analyze() can hold it for
        # an entire XLA compile — a dispatch must never wait on that
        self._lock = threading.Lock()
        self._alock = threading.Lock()

    def note_invocation(self) -> None:
        # locked: the profile context deliberately follows pipelines
        # onto producer/driver threads, so one record takes concurrent
        # dispatches — an unlocked += would drop counts
        with self._lock:
            self.invocations += 1

    def note_device_time(self, seconds: float) -> None:
        with self._lock:
            self.device_time_s += seconds

    def note_compile(self, seconds: float, fn, args) -> None:
        """Record a (first-call) compile and capture the call's abstract
        shapes for lazy analysis. jit retraces for later shape buckets
        silently, so the analysis describes the first bucket — scan
        padding keeps buckets stable within a query, and the numbers
        are per-invocation estimates, not an audit."""
        with self._lock:
            self.compiles += 1
            self.compile_seconds += seconds
        if self._avals is None:
            try:
                import jax
                import jax.numpy as jnp
                self._avals = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(
                        jnp.shape(x), jnp.result_type(x)), args)
                self._fn = fn
            except Exception:
                self._avals = None

    def analyze(self) -> Dict:
        """Lazy XLA introspection: FLOPs / bytes-accessed from
        ``lowered.cost_analysis()`` (per invocation), arg/output/temp
        bytes + generated code size from ``compiled.memory_analysis()``.
        The memory half pays one extra XLA compile the first time it is
        asked for (the jit dispatch cache is separate) — which is why
        this runs at table-read/EXPLAIN-render time, never per call.
        Fields are None when the backend doesn't support the API."""
        with self._alock:
            if self._analysis is not None:
                return self._analysis
            out: Dict = {"flops": None, "bytes_accessed": None,
                         "arg_bytes": None, "output_bytes": None,
                         "temp_bytes": None, "generated_code_bytes": None}
            fn, avals = self._fn, self._avals
            if fn is not None and avals is not None:
                lowered = None
                try:
                    lowered = fn.lower(*avals)
                    ca = lowered.cost_analysis() or {}
                    if isinstance(ca, (list, tuple)):
                        ca = ca[0] if ca else {}
                    if "flops" in ca:
                        out["flops"] = float(ca["flops"])
                    if "bytes accessed" in ca:
                        out["bytes_accessed"] = float(ca["bytes accessed"])
                except Exception:
                    pass
                try:
                    if lowered is not None:
                        ma = lowered.compile().memory_analysis()
                        if ma is not None:
                            out["arg_bytes"] = int(
                                ma.argument_size_in_bytes)
                            out["output_bytes"] = int(
                                ma.output_size_in_bytes)
                            out["temp_bytes"] = int(ma.temp_size_in_bytes)
                            out["generated_code_bytes"] = int(
                                ma.generated_code_size_in_bytes)
                except Exception:
                    pass
            self._analysis = out
            return out

    def to_row(self, analyze: bool = True) -> Dict:
        doc = {
            "name": self.name, "static_key": self.static_key,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "invocations": self.invocations,
            "device_time_s": round(self.device_time_s, 6),
        }
        a = self.analyze() if analyze else (self._analysis or {})
        for k in ("flops", "bytes_accessed", "arg_bytes", "output_bytes",
                  "temp_bytes", "generated_code_bytes"):
            doc[k] = a.get(k)
        return doc


class ExecutableRegistry:
    """Process-wide (name, static key) -> ExecutableRecord, bounded.
    The feed of ``system.runtime.executables``."""

    def __init__(self, max_records: int = 4096):
        self._records: Dict[Tuple[str, str], ExecutableRecord] = {}
        self._max = max_records
        self._lock = threading.Lock()
        #: >0: time every Nth invocation of each entry even without a
        #: profile context (always-on sampling; 0 = off, the default —
        #: plain queries must pay nothing)
        self.sample_every = 0

    def register(self, name: str, static_key=()) -> ExecutableRecord:
        # identity keys on the FULL repr — two fused chains sharing a
        # long prefix must stay distinct records; only the displayed
        # static_key column is truncated
        key_repr = repr(static_key)
        k = (name, key_repr)
        rec = self._records.get(k)
        if rec is None:
            with self._lock:
                rec = self._records.get(k)
                if rec is None:
                    if len(self._records) >= self._max:
                        self._evict_one_locked()
                    shown = (key_repr if len(key_repr) <= 160
                             else key_repr[:157] + "...")
                    rec = ExecutableRecord(name, shown)
                    rec._key_repr = key_repr
                    self._records[k] = rec
        return rec

    def _evict_one_locked(self) -> None:
        # drop the coldest record (fewest invocations, then oldest) —
        # the cap is a leak guard, not a working set (4096 entries is
        # far beyond any real query mix), so the victim should be a
        # one-off key shape, never a hot import-time entry
        victim = min(self._records,
                     key=lambda x: (self._records[x].invocations,
                                    self._records[x].created_at))
        self._records[victim].evicted = True
        del self._records[victim]

    def readmit(self, rec: ExecutableRecord) -> None:
        """Re-insert a record the cap evicted while its _TimedEntry was
        still live (the entry caches the record forever, so without
        this the busiest kernels could update a detached ledger the
        tables never see). Called from the dispatch path only when
        ``rec.evicted`` is set — i.e. ~never."""
        k = (rec.name, rec._key_repr)
        with self._lock:
            if k not in self._records:
                if len(self._records) >= self._max:
                    self._evict_one_locked()
                self._records[k] = rec
            rec.evicted = False

    def snapshot(self, analyze: bool = True) -> List[Dict]:
        with self._lock:
            recs = list(self._records.values())
        recs.sort(key=lambda r: (-r.device_time_s, -r.compile_seconds))
        return [r.to_row(analyze=analyze) for r in recs]

    def reset(self) -> None:
        with self._lock:
            # live _TimedEntries keep dispatching into the dropped
            # records; marking them evicted lets the next dispatch
            # readmit each, so a reset zeroes the view without making
            # cached kernels permanently invisible
            for rec in self._records.values():
                rec.evicted = True
            self._records.clear()


#: the process-wide executable registry
EXECUTABLES = ExecutableRegistry()


# -- profile context ----------------------------------------------------------

class ProfileSession:
    """Marker held by the ``_ACTIVE`` contextvar while a query profiles
    (one per profiled query; carries nothing yet — attribution state
    lives on the query's StatsCollector)."""

    __slots__ = ()


_SESSION = ProfileSession()


@contextlib.contextmanager
def profiled(on: bool = True):
    """Enable device-time bracketing for jit dispatches made under this
    context (same thread/context only — background prefetch threads stay
    unbracketed so overlapped staging is never serialized)."""
    if not on:
        yield
        return
    token = _ACTIVE.set(_SESSION)
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def profiling_active() -> bool:
    return _ACTIVE.get() is not None


@contextlib.contextmanager
def operator_scope(stats, node):
    """Attribute jit dispatches made under this context to ``node`` on
    ``stats`` (a StatsCollector). Innermost scope wins — nested operator
    iterators re-set it around their own frames."""
    token = _OP.set((stats, node))
    try:
        yield
    finally:
        _OP.reset(token)


def current_operator():
    return _OP.get()


def should_profile_call(record: ExecutableRecord) -> bool:
    """Hot-path gate: profile context active, or the always-on sampler
    elected this invocation."""
    if _ACTIVE.get() is not None:
        return True
    se = EXECUTABLES.sample_every
    return bool(se) and record.invocations % se == 0


def profiled_call(record: ExecutableRecord, fn, args):
    """One bracketed dispatch: run, block until the device finishes,
    charge the interval to the executable and to the operator whose
    frame made the call. Under a profile context every call is
    bracketed, so no queued async work can leak into the interval. In
    sampling mode (``sample_every``) the neighbouring calls are NOT
    bracketed, so drain the sampled call's input producers first —
    otherwise the whole queued pipeline would be billed to this one
    executable. (Unrelated queued kernels can still overlap; sampled
    numbers are estimates, not an audit.)"""
    import jax
    if _ACTIVE.get() is None:
        try:
            jax.block_until_ready(args)
        except Exception:
            pass
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    record.note_device_time(dt)
    _DEVICE_SECONDS.inc(dt)
    op = _OP.get()
    if op is not None:
        stats, node = op
        rd = getattr(stats, "record_device", None)
        if rd is not None:
            rd(node, dt, record)
    return out


# -- verdict (tf.data's input-bound vs compute-bound framing) -----------------

def cost_verdict(stats) -> Optional[Dict]:
    """Classify a profiled query: device time attributed to non-scan
    operators (compute) vs scan-side host time — scan operators'
    exclusive wall (decode/staging) plus consumer prefetch stall
    (input). None when nothing was profiled."""
    from ..planner.plan import TableScanNode
    compute_s = 0.0
    scan_wall_s = 0.0
    for node, st in list(stats.by_node.items()):
        dev = getattr(st, "device_time_s", 0.0)
        if isinstance(node, TableScanNode):
            child_wall = sum(
                (stats.stats_for(c).wall_s
                 if stats.stats_for(c) is not None else 0.0)
                for c in node.children)
            scan_wall_s += max(st.wall_s - child_wall, 0.0)
        else:
            compute_s += dev
    input_s = scan_wall_s + getattr(stats, "prefetch_stall_s", 0.0)
    if compute_s <= 0.0 and input_s <= 0.0:
        return None
    if input_s > 2.0 * compute_s:
        verdict = "input-bound"
    elif compute_s > 2.0 * input_s:
        verdict = "compute-bound"
    else:
        verdict = "balanced"
    return {"verdict": verdict, "compute_s": compute_s,
            "input_s": input_s}


# -- HBM telemetry ------------------------------------------------------------

def sample_hbm(devices=None, registry=None) -> List[Dict]:
    """Sample ``device.memory_stats()`` into per-device gauges
    (``hbm_in_use_bytes.<dev>`` / ``hbm_peak_bytes.<dev>``) and return
    the per-device docs. Backends without memory stats (XLA:CPU returns
    None) yield an empty list — callers treat that as "no HBM story",
    not an error."""
    reg = registry if registry is not None else REGISTRY
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            return []
    out: List[Dict] = []
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        in_use = int(ms.get("bytes_in_use", 0) or 0)
        peak = int(ms.get("peak_bytes_in_use", in_use) or in_use)
        limit = int(ms.get("bytes_limit", 0) or 0)
        label = f"{getattr(d, 'platform', 'dev')}{getattr(d, 'id', 0)}"
        reg.gauge(f"hbm_in_use_bytes.{label}").set(in_use)
        reg.gauge(f"hbm_peak_bytes.{label}").set(peak)
        out.append({"device": label, "device_id": getattr(d, "id", 0),
                    "bytes_in_use": in_use,
                    "peak_bytes_in_use": peak, "bytes_limit": limit})
    return out


def hbm_totals(devices=None, registry=None) -> Dict[str, int]:
    """Summed HBM sample for heartbeat payloads: zeros when the backend
    has no memory stats (the coordinator then shows 0, not stale)."""
    docs = sample_hbm(devices, registry)
    return {
        "bytesInUse": sum(d["bytes_in_use"] for d in docs),
        "peakBytes": sum(d["peak_bytes_in_use"] for d in docs),
        "devices": len(docs),
    }


# -- device-trace merging (--profile-out) -------------------------------------

def find_device_traces(profile_dir: str) -> List[str]:
    """Chrome-trace files from the NEWEST profiling session under a
    profile dir (``plugins/profile/<ts>/*.trace.json[.gz]``).
    ``jax.profiler`` leaves one ``<ts>`` subdir per ``start_trace``, so
    a reused ``--profile-out`` DIR accumulates sessions — merging any
    but the latest would interleave a past run's kernels (with that
    run's absolute timestamps) onto the current host timeline."""
    pats = [os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json")]
    found: List[str] = []
    for p in pats:
        found.extend(glob.glob(p))
    if not found:
        return []
    found.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    newest_session = os.path.dirname(found[0])
    return [p for p in found if os.path.dirname(p) == newest_session]


def load_trace_events(path: str) -> List[Dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return list(doc.get("traceEvents") or [])
    return list(doc or [])


def merge_chrome_traces(host: Dict, device_events: List[Dict]) -> Dict:
    """One Chrome-trace object holding the span tracer's host events AND
    the XLA profiler's device tracks. Device pids are remapped above the
    host range so Perfetto shows them as separate processes instead of
    colliding lanes."""
    events = list(host.get("traceEvents") or [])
    base = max([int(e.get("pid", 0)) for e in events] + [0]) + 1000
    remap: Dict[int, int] = {}
    for e in device_events:
        e = dict(e)
        pid = e.get("pid")
        if isinstance(pid, int):
            if pid not in remap:
                remap[pid] = base + len(remap)
            e["pid"] = remap[pid]
        e.setdefault("cat", "device")
        events.append(e)
    out = dict(host)
    out["traceEvents"] = events
    return out


def write_merged_trace(path: str, spans: List[Dict],
                       profile_dir: str) -> str:
    """Merge the span tracer's export with whatever device trace(s)
    ``jax.profiler`` wrote under ``profile_dir`` and write one
    Perfetto-loadable JSON file. Missing/unreadable device traces
    degrade to a host-only trace — the file always lands. Mesh-path
    queries additionally contribute a "mesh rounds" track (one lane
    per attribution bucket) from the flight recorder, timestamped on
    the same epoch-anchored clock as the host spans."""
    from .flight import FLIGHTS, chrome_events
    from .trace import chrome_trace
    host = chrome_trace(spans)
    device_events: List[Dict] = []
    for p in find_device_traces(profile_dir):
        try:
            device_events.extend(load_trace_events(p))
        except Exception:
            continue
    for fl in FLIGHTS.snapshot():
        device_events.extend(chrome_events(fl))
    merged = merge_chrome_traces(host, device_events)
    with open(path, "w") as f:
        json.dump(merged, f)
    return path

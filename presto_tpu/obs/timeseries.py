"""In-process, fixed-memory time-series plane over the metrics registry.

Every other observability surface in the repo is a *snapshot*: the
``/v1/metrics`` exposition and ``system.runtime.metrics`` render whatever
the counters/gauges/histograms hold *right now*, and histogram quantiles
are derived from process-lifetime cumulative bucket counts — a latency
spike vanishes into the lifetime average within minutes.  This module
adds the time axis (the Monarch insight: control loops and SLO
enforcement consume *windowed* series, never raw counters):

- a background **sampler** (``timeseries.sample-interval-s`` config key,
  default 5s) snapshots :data:`presto_tpu.obs.metrics.REGISTRY` into
  typed series, each a bounded ring (``timeseries.retention-points``,
  default 360 points = 30 min at the default cadence) so memory is fixed
  no matter how long the process lives;
- **counters** become windowed *rates* via successive-sample deltas;
- **gauges** sample directly;
- **histograms** store cumulative ``(count, sum, bucket_counts)``
  tuples, and windowed quantiles are derived by *differencing* the
  cumulative bucket counts between the window's first and last samples —
  "p95 over the last 5 minutes" finally means what it says;
- :meth:`TimeSeriesStore.range` reads any series back with
  ``sum/avg/max/rate/quantile`` reducers;
- :meth:`TimeSeriesStore.record` accepts externally-fed points so the
  coordinator can federate worker-side series that arrive through the
  heartbeat/poll path (``exec/cluster.py``).

Deliberate non-goals: no persistence, no cross-process aggregation
protocol, no downsampling tiers.  The store is one process's bounded
ring; federation is "the coordinator records what heartbeats told it".

Windowed-delta semantics (shared by ``rate`` and ``quantile``): the
baseline is the latest sample at or before ``now - window`` (so a full
window is covered when history allows) or, failing that, the earliest
sample inside the window; the end point is the latest sample at or
before ``now``.  At least two distinct samples are required — otherwise
the reducer reports ``None`` rather than inventing a number.

Everything is import-safe and near-free when idle: no thread runs until
:meth:`TimeSeriesStore.ensure_started` (called from server startup) and
an unstarted store costs one dict.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .._devtools.lockcheck import checked_lock
from .metrics import REGISTRY, MetricsRegistry, _quantile

DEFAULT_SAMPLE_INTERVAL_S = 5.0
DEFAULT_RETENTION_POINTS = 360

_REDUCERS = ("sum", "avg", "max", "rate", "quantile")


class _Series:
    """One named series: a bounded ring of ``(t, value)`` points.

    ``kind`` is the registry kind ("counter" | "gauge" | "histogram").
    Counter/gauge points hold a float; histogram points hold the
    cumulative ``(count, sum, bucket_counts)`` tuple so windowed
    quantiles can be derived by differencing.
    """

    __slots__ = ("name", "kind", "points", "bounds")

    def __init__(self, name: str, kind: str, retention: int,
                 bounds: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.kind = kind
        self.points: deque = deque(maxlen=retention)
        self.bounds = bounds  # histogram bucket bounds (finite ones)


def _per_bucket(cumulative: Sequence[int]) -> List[int]:
    """Cumulative bucket counts -> per-bucket counts (what
    :func:`presto_tpu.obs.metrics._quantile` consumes)."""
    out: List[int] = []
    prev = 0
    for c in cumulative:
        out.append(max(0, c - prev))
        prev = c
    return out


def _window_pair(points: Sequence[Tuple[float, object]], window: float,
                 now: float):
    """(baseline, end) points for a windowed delta, or ``None``.

    Baseline prefers the latest point at or before ``now - window``
    (full-window coverage); otherwise the earliest point inside the
    window.  End is the latest point at or before ``now``.  Tolerates
    out-of-order timestamps (federated points and synthetic test
    clocks interleave with the wall-clock sampler).
    """
    start = now - window
    base = None
    end = None
    first_in = None
    for pt in points:
        t = pt[0]
        if t > now:
            continue
        if t <= start:
            if base is None or t >= base[0]:
                base = pt
        elif first_in is None or t < first_in[0]:
            first_in = pt
        if end is None or t >= end[0]:
            end = pt
    if base is None:
        base = first_in
    if base is None or end is None or end[0] <= base[0]:
        return None
    return base, end


class TimeSeriesStore:
    """Bounded in-memory store of typed series sampled from a registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self._registry = registry if registry is not None else REGISTRY
        self._lock = checked_lock("timeseries.store")
        self._series: Dict[str, _Series] = {}
        self._retention = DEFAULT_RETENTION_POINTS
        self._interval = DEFAULT_SAMPLE_INTERVAL_S
        self._listeners: List[Callable[[float], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        env = os.environ.get("PRESTO_TPU_TIMESERIES", "").strip().lower()
        self._enabled = env not in ("off", "0", "false")

    # -- configuration ------------------------------------------------------

    def configure(self, sample_interval_s: Optional[float] = None,
                  retention_points: Optional[int] = None) -> None:
        """Set sampler cadence / per-series ring size.

        Shrinking ``retention_points`` re-rings existing series (keeps
        the newest points); growing applies on the next append.
        """
        with self._lock:
            if sample_interval_s is not None:
                self._interval = max(0.05, float(sample_interval_s))
            if retention_points is not None:
                retention = max(2, int(retention_points))
                if retention != self._retention:
                    self._retention = retention
                    for s in self._series.values():
                        s.points = deque(s.points, maxlen=retention)

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry

    @property
    def sample_interval_s(self) -> float:
        return self._interval

    @property
    def retention_points(self) -> int:
        return self._retention

    def add_listener(self, fn: Callable[[float], None]) -> None:
        """Register ``fn(now)`` to run after every sampler tick (used by
        the SLO tracker).  Idempotent per function object."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    # -- ingest -------------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> float:
        """Snapshot the registry into the rings; returns the timestamp.

        Collects outside the store lock (the registry has its own), then
        appends under it.  Also invoked by the background sampler; tests
        drive it directly with an explicit ``now`` for synthetic time.
        """
        t = time.time() if now is None else float(now)
        collected = self._registry.collect()
        with self._lock:
            for state in collected:
                name = state["name"]
                kind = state["kind"]
                s = self._series.get(name)
                if kind == "histogram":
                    buckets = state["buckets"]
                    if s is None:
                        bounds = tuple(le for le, _ in buckets
                                       if le != float("inf"))
                        s = _Series(name, kind, self._retention, bounds)
                        self._series[name] = s
                    value = (int(state["count"]), float(state["sum"]),
                             tuple(c for _, c in buckets))
                else:
                    if s is None:
                        s = _Series(name, kind, self._retention)
                        self._series[name] = s
                    value = float(state["value"])
                s.points.append((t, value))
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(t)
            except Exception:
                pass
        return t

    def record(self, name: str, value: float, now: Optional[float] = None,
               kind: str = "gauge") -> None:
        """Append one externally-fed point (coordinator federation of
        worker series arriving via heartbeats)."""
        t = time.time() if now is None else float(now)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = _Series(name, kind, self._retention)
                self._series[name] = s
            s.points.append((t, float(value)))

    # -- background sampler -------------------------------------------------

    def ensure_started(self) -> bool:
        """Start the daemon sampler once per process (idempotent).

        Returns True when a sampler is (now) running; False when the
        store is disabled via ``PRESTO_TPU_TIMESERIES=off``.
        """
        if not self._enabled:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop_event = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="timeseries-sampler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            self._stop_event.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    def _loop(self) -> None:
        stop = self._stop_event
        while not stop.wait(self._interval):
            t0 = time.perf_counter()
            try:
                self.sample()
            except Exception:
                pass
            cost = time.perf_counter() - t0
            self._registry.counter("timeseries_samples_total").inc()
            self._registry.counter("timeseries_sample_seconds_total").inc(cost)

    # -- reads --------------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, name: str) -> Optional[str]:
        with self._lock:
            s = self._series.get(name)
            return s.kind if s is not None else None

    def points(self, name: str, window: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, object]]:
        """Raw ring points for ``name`` (newest last), optionally
        restricted to ``[now - window, now]``."""
        t = time.time() if now is None else float(now)
        with self._lock:
            s = self._series.get(name)
            pts = list(s.points) if s is not None else []
        if window is not None:
            start = t - float(window)
            pts = [p for p in pts if start <= p[0] <= t]
        return pts

    def rate(self, name: str, window: float,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed per-second rate of a counter (successive-sample
        delta over elapsed time); ``None`` without two samples."""
        t = time.time() if now is None else float(now)
        with self._lock:
            s = self._series.get(name)
            pts = list(s.points) if s is not None else []
        pair = _window_pair(pts, float(window), t)
        if pair is None:
            return None
        (t0, v0), (t1, v1) = pair
        delta = float(v1) - float(v0)
        if delta < 0:  # registry was reset mid-window
            return None
        return delta / (t1 - t0)

    def window_counts(self, name: str, window: float,
                      now: Optional[float] = None):
        """Histogram window delta: ``(count, sum, bucket_counts, bounds)``
        differenced between the window's baseline and end samples, or
        ``None``.  ``bucket_counts`` are *cumulative* window deltas
        aligned with ``bounds + (+Inf,)``."""
        t = time.time() if now is None else float(now)
        with self._lock:
            s = self._series.get(name)
            if s is None or s.kind != "histogram":
                return None
            pts = list(s.points)
            bounds = s.bounds or ()
        pair = _window_pair(pts, float(window), t)
        if pair is None:
            return None
        (_, (c0, s0, b0)), (_, (c1, s1, b1)) = pair
        dc = c1 - c0
        if dc < 0:  # registry was reset mid-window
            return None
        db = tuple(max(0, x1 - x0) for x0, x1 in zip(b0, b1))
        return dc, s1 - s0, db, bounds

    def window_quantile(self, name: str, window: float, q: float,
                        now: Optional[float] = None) -> Optional[float]:
        """Quantile of a histogram over the window, from differenced
        cumulative bucket counts.  The estimate interpolates inside the
        winning bucket and clamps to the highest finite bound for the
        +Inf bucket (no windowed min/max exists).
        """
        delta = self.window_counts(name, window, now)
        if delta is None:
            return None
        count, _total, bucket_counts, bounds = delta
        if count <= 0 or not bounds:
            return None
        hi = bounds[-1]
        return _quantile(q, count, _per_bucket(bucket_counts),
                         tuple(bounds), 0.0, hi)

    def range(self, name: str, window: float, reduce: str = "avg",
              q: float = 0.95, labels: Optional[str] = None,
              now: Optional[float] = None) -> Optional[float]:
        """One reduced value for ``name`` over the trailing ``window``.

        ``labels`` (a dotted tail, e.g. ``"serving.dash"``) is appended
        to ``name`` — the registry collapses labels into dotted names,
        so ``range("serving_latency_seconds", 300, "quantile",
        labels="serving.dash")`` reads the per-group series.

        Reducers: ``sum``/``avg``/``max`` fold raw gauge (or counter
        level) points; ``rate`` is the windowed counter rate;
        ``quantile`` is the windowed histogram quantile ``q``.
        Returns ``None`` when the window lacks data.
        """
        if reduce not in _REDUCERS:
            raise ValueError(f"unknown reducer {reduce!r}; "
                             f"expected one of {_REDUCERS}")
        if labels:
            name = f"{name}.{labels}"
        if reduce == "rate":
            return self.rate(name, window, now=now)
        if reduce == "quantile":
            return self.window_quantile(name, window, q, now=now)
        pts = [p for p in self.points(name, window=window, now=now)
               if not isinstance(p[1], tuple)]
        if not pts:
            return None
        vals = [float(v) for _, v in pts]
        if reduce == "sum":
            return sum(vals)
        if reduce == "max":
            return max(vals)
        return sum(vals) / len(vals)

    # -- system.runtime.timeseries ------------------------------------------

    def rows(self, max_points_per_series: int = 32,
             now: Optional[float] = None) -> List[Tuple]:
        """``system.runtime.timeseries`` rows: ``(name, kind, ts, value)``.

        Derived, not raw: counters emit per-interval rates (name suffixed
        ``.rate``), histograms emit per-interval windowed ``.p50/.p95/
        .p99`` plus a ``.rate`` of observations, gauges emit raw points.
        Capped at the newest ``max_points_per_series`` intervals per
        series so the table stays scannable.
        """
        with self._lock:
            snap = [(s.name, s.kind, list(s.points), s.bounds)
                    for s in self._series.values()]
        out: List[Tuple] = []
        for name, kind, pts, bounds in snap:
            pts = pts[-(max_points_per_series + 1):]
            if kind == "gauge":
                out.extend((name, kind, t, float(v))
                           for t, v in pts[-max_points_per_series:])
                continue
            for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
                dt = t1 - t0
                if dt <= 0:
                    continue
                if kind == "counter":
                    out.append((f"{name}.rate", kind, t1,
                                (float(v1) - float(v0)) / dt))
                    continue
                c0, _s0, b0 = v0
                c1, _s1, b1 = v1
                dc = c1 - c0
                out.append((f"{name}.rate", kind, t1, max(0, dc) / dt))
                if dc <= 0 or not bounds:
                    continue
                db = _per_bucket([max(0, x1 - x0)
                                  for x0, x1 in zip(b0, b1)])
                for label, q in (("p50", 0.5), ("p95", 0.95),
                                 ("p99", 0.99)):
                    est = _quantile(q, dc, db, tuple(bounds), 0.0,
                                    bounds[-1])
                    out.append((f"{name}.{label}", kind, t1, float(est)))
        out.sort(key=lambda r: (r[0], r[2]))
        return out

    def window_quantile_rows(self, window: float = 300.0,
                             now: Optional[float] = None
                             ) -> List[Tuple[str, float]]:
        """Latest windowed quantiles per histogram series, named like
        the lifetime flattening with a window tag:
        ``("query_seconds.p95_5m", 0.012)``.  Series without two
        samples in the window are omitted (windowed means windowed —
        no lifetime fallback)."""
        label = f"{max(1, int(round(window / 60.0)))}m"
        out: List[Tuple[str, float]] = []
        for name in self.series_names():
            if self.kind(name) != "histogram":
                continue
            for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                v = self.window_quantile(name, window, q, now=now)
                if v is not None:
                    out.append((f"{name}.{tag}_{label}", float(v)))
        return out

    def derived_points(self, name: str, window: float, q: float = 0.95,
                       now: Optional[float] = None
                       ) -> List[Tuple[float, float]]:
        """Plottable ``(t, value)`` points for one series over the
        window: gauges raw, counters per-interval rates, histograms
        per-interval quantile ``q`` (empty intervals skipped)."""
        t = time.time() if now is None else float(now)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            kind, pts = s.kind, list(s.points)
            bounds = s.bounds or ()
        start = t - float(window)
        if kind == "gauge":
            return [(pt, float(v)) for pt, v in pts
                    if start <= pt <= t]
        out: List[Tuple[float, float]] = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if t1 < start or t1 > t or t1 <= t0:
                continue
            if kind == "counter":
                out.append((t1, (float(v1) - float(v0)) / (t1 - t0)))
                continue
            c0, _s0, b0 = v0
            c1, _s1, b1 = v1
            dc = c1 - c0
            if dc <= 0 or not bounds:
                continue
            per = _per_bucket([max(0, x1 - x0)
                               for x0, x1 in zip(b0, b1)])
            out.append((t1, float(_quantile(q, dc, per, tuple(bounds),
                                            0.0, bounds[-1]))))
        return out

    def history_doc(self, query_string: str) -> Tuple[int, Dict]:
        """``GET /v1/metrics/history?name=&window=[&reduce=&q=]`` body,
        shared by the coordinator and worker handlers: (status, doc).

        The doc carries the derived plottable points plus, when a
        ``reduce`` parameter names a reducer, one reduced scalar over
        the whole window.
        """
        from urllib.parse import parse_qs
        params = parse_qs(query_string or "")

        def one(key, default=None):
            vals = params.get(key)
            return vals[0] if vals else default

        name = one("name")
        if not name:
            return 400, {"error": "missing required parameter 'name'",
                         "series": self.series_names()}
        try:
            window = float(one("window", 300.0))
            q = float(one("q", 0.95))
        except ValueError as e:
            return 400, {"error": f"bad parameter: {e}"}
        kind = self.kind(name)
        if kind is None:
            return 404, {"error": f"unknown series {name!r}",
                         "series_count": len(self.series_names())}
        now = time.time()
        doc: Dict = {
            "name": name, "kind": kind, "window_s": window,
            "sampled_at": now,
            "points": [[t, v] for t, v in
                       self.derived_points(name, window, q, now=now)],
        }
        reduce_ = one("reduce")
        if reduce_:
            try:
                doc["reduce"] = reduce_
                doc["reduced"] = self.range(name, window, reduce_, q=q,
                                            now=now)
            except ValueError as e:
                return 400, {"error": str(e)}
        return 200, doc

    # -- lifecycle ----------------------------------------------------------

    def reset(self, keep_listeners: bool = True) -> None:
        """Drop all series (tests).  The sampler thread, configuration,
        and (by default) listeners survive."""
        with self._lock:
            self._series.clear()
            if not keep_listeners:
                self._listeners.clear()


TIMESERIES = TimeSeriesStore()

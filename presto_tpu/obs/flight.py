"""Mesh flight recorder: per-round wall-clock attribution for SPMD.

ROADMAP item 1 claims the mesh loses to one device because the
per-round host control plane (per-batch dispatch, host-mediated
repartition rounds, control-scalar fetches) eats the parallelism —
but the MULTICHIP pins only record rows/s, so nothing could say
*which* of dispatch, staging, sync, or repartition dominates. This
module is the measurement: every host-observable event on the mesh
path becomes a timestamped **round record**, and a post-query
attribution pass reconciles measured wall time into named buckets
plus a cross-round critical path per shard.

Design constraints, in order:

- **Cheap.** ``record()`` is one perf_counter read and one list
  append under a lock; a query producing thousands of rounds must
  stay under 1% of its wall (asserted in tests/test_mesh_flight.py).
  No device work, no allocation beyond the record dict.
- **Honest.** The buckets are *host-blocking wall* observed at each
  instrumentation site; async device time the host never waits for is
  invisible by construction, so ``finish()`` reports the reconciled
  fraction explicitly instead of inventing a remainder.
- **Ambient.** Instrumentation sites (exec/distributed.py, the scan
  cache's prefetch stall accounting) reach the active recorder through
  a contextvar — no signature threading through the executor.

Record kinds map onto six attribution buckets:

==============  ===================  =====================================
kind            bucket               instrumentation site
==============  ===================  =====================================
dispatch        dispatch_overhead    ``_smap`` host-side dispatch call
drain           device_compute       result gather / final ``to_pylist``
sync            control_sync         ``device-sync`` control-scalar fetch
staging         host_staging         ``_stage_parts`` host->device upload
resplit         repartition          ``_PartitionMap`` epoch re-split
repartition     repartition          all_to_all exchange round
stall           stall                scan-prefetch stall (cache feed)
==============  ===================  =====================================

``dispatch`` wall on the forced-CPU mesh *contains* the device compute
(CPU "devices" execute synchronously inside the dispatch call); on a
real async backend it is the host-side call overhead only and the
device wall shows up at the next blocking point. Either way the sum of
buckets is what the host measurably spent, which is the quantity the
item-1 exchange overhaul must shrink.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .metrics import REGISTRY
from .trace import _now

#: attribution bucket names, display order (docs/observability.md)
BUCKETS: Tuple[str, ...] = (
    "device_compute", "dispatch_overhead", "host_staging",
    "control_sync", "repartition", "stall")

#: record kind -> attribution bucket
KIND_BUCKET: Dict[str, str] = {
    "dispatch": "dispatch_overhead",
    "drain": "device_compute",
    "sync": "control_sync",
    "staging": "host_staging",
    "resplit": "repartition",
    "repartition": "repartition",
    "stall": "stall",
}

#: ``system.runtime.mesh_rounds`` column order — printer and connector
#: both render from this so the EXPLAIN ANALYZE section and the system
#: table can never drift apart
ROUND_COLUMNS: Tuple[str, ...] = (
    "query_id", "round", "stage", "kind", "bucket", "t_start",
    "wall_s", "rows", "bytes", "loads", "blocking", "rounds")

_FLIGHT_QUERIES = REGISTRY.counter("mesh_flight_queries_total")
_ROUNDS_TOTAL = REGISTRY.counter("mesh_rounds_total")
_ROUND_SECONDS = REGISTRY.histogram("mesh_round_seconds")
_OVERHEAD_TOTAL = REGISTRY.counter("mesh_flight_overhead_seconds_total")
_ATTR_TOTALS = {
    b: REGISTRY.counter(f"mesh_attr_{b}_seconds_total")
    for b in BUCKETS
}


class FlightRecorder:
    """Per-query round timeline + post-query attribution.

    One instance per mesh-path query execution, installed as
    :data:`CURRENT_FLIGHT` for the duration. Thread-safe: scan streams
    and the executor may record from worker threads.
    """

    __slots__ = ("query_id", "n_devices", "started_at", "_records",
                 "_sums", "_lock", "attribution")

    def __init__(self, query_id: str = "", n_devices: int = 1):
        self.query_id = query_id
        self.n_devices = max(int(n_devices), 1)
        self.started_at = _now()
        self._records: List[dict] = []
        self._sums: Dict[str, float] = {}
        self._lock = threading.Lock()
        #: set by :meth:`finish`
        self.attribution: Optional[dict] = None

    # -- hot path -------------------------------------------------------------
    def record(self, kind: str, stage: int = -1, wall: float = 0.0,
               rows: int = 0, nbytes: int = 0,
               loads: Optional[Sequence[int]] = None,
               blocking: bool = True, t_start: float = 0.0,
               rounds: int = 1) -> None:
        """Append one round record. ``wall`` is host-blocking seconds
        measured by the caller; ``loads`` is the per-shard row load of
        the round (feeds the critical path); ``t_start`` is the
        trace-epoch wall clock at the start of the interval (defaults
        to now - wall); ``rounds`` is the number of DEVICE rounds the
        dispatch covers — a fused multi-round program (lax.fori_loop
        over exchange rounds) is one host record with rounds=R, so the
        per-fused-dispatch timeline still exposes how much device-side
        looping each host touch amortizes."""
        rec = {
            "kind": kind,
            "stage": int(stage),
            "t": t_start if t_start else _now() - wall,
            "wall": float(wall),
            "rows": int(rows),
            "bytes": int(nbytes),
            "loads": tuple(int(x) for x in loads) if loads else None,
            "blocking": bool(blocking),
            "rounds": max(int(rounds), 1),
        }
        with self._lock:
            rec["round"] = len(self._records)
            self._records.append(rec)
            self._sums[kind] = self._sums.get(kind, 0.0) + rec["wall"]

    def kind_wall(self, kind: str) -> float:
        """Running wall-seconds total of one record kind — lets nesting
        instrumentation subtract already-recorded inner intervals (the
        scan pull loop nets out prefetch stalls) without re-scanning
        the record list."""
        with self._lock:
            return self._sums.get(kind, 0.0)

    @contextlib.contextmanager
    def timed(self, kind: str, stage: int = -1, rows: int = 0,
              nbytes: int = 0, loads: Optional[Sequence[int]] = None,
              blocking: bool = True):
        """Measure a host-blocking interval and record it."""
        t0 = time.perf_counter()
        w0 = _now()
        try:
            yield
        finally:
            self.record(kind, stage=stage,
                        wall=time.perf_counter() - t0, rows=rows,
                        nbytes=nbytes, loads=loads, blocking=blocking,
                        t_start=w0)

    # -- read side ------------------------------------------------------------
    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._records]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # -- attribution ----------------------------------------------------------
    def finish(self, wall_s: float) -> dict:
        """Reconcile the round timeline against the measured query wall
        and publish the flight: bucket seconds, dominant bucket,
        reconciled fraction, per-shard critical path, metrics, and the
        process-wide :data:`FLIGHTS` log."""
        records = self.records()
        buckets = {b: 0.0 for b in BUCKETS}
        per_shard = [0.0] * self.n_devices
        for r in records:
            bucket = KIND_BUCKET.get(r["kind"], "dispatch_overhead")
            buckets[bucket] += r["wall"]
            loads = r["loads"]
            if loads and len(loads) == self.n_devices and max(loads):
                # critical path: the straggler shard accrues the full
                # round wall (the round cannot finish before it does);
                # the rest accrue their proportional share
                peak = max(loads)
                for i, ld in enumerate(loads):
                    per_shard[i] += r["wall"] * (ld / peak)
            else:
                # no per-shard signal: the round gates every shard
                for i in range(self.n_devices):
                    per_shard[i] += r["wall"]
        bucketed = sum(buckets.values())
        wall_s = max(float(wall_s), 1e-9)
        overhead = bucketed - buckets["device_compute"]
        dominant = max(BUCKETS, key=lambda b: buckets[b])
        slowest = max(range(self.n_devices),
                      key=lambda i: per_shard[i]) if per_shard else 0
        attribution = {
            "query_id": self.query_id,
            "n_devices": self.n_devices,
            "wall_s": round(wall_s, 6),
            "rounds": len(records),
            # device rounds covered by those records: > rounds when
            # fused dispatches loop multiple exchange rounds on device
            "device_rounds": sum(r.get("rounds", 1) for r in records),
            "buckets": {b: round(s, 6) for b, s in buckets.items()},
            "dominant_bucket": dominant,
            "reconciled_pct": round(
                min(bucketed / wall_s, 1.0) * 100.0, 2),
            "overhead_s": round(max(overhead, 0.0), 6),
            "critical_path": {
                "per_shard_s": [round(s, 6) for s in per_shard],
                "slowest_shard": slowest,
            },
        }
        self.attribution = attribution
        _FLIGHT_QUERIES.inc()
        _ROUNDS_TOTAL.inc(len(records))
        for r in records:
            _ROUND_SECONDS.observe(r["wall"])
        _OVERHEAD_TOTAL.inc(max(overhead, 0.0))
        for b, s in buckets.items():
            if s:
                _ATTR_TOTALS[b].inc(s)
        FLIGHTS.add(self)
        return attribution


class FlightLog:
    """Bounded process-wide log of finished flights — the backing
    store of ``system.runtime.mesh_rounds`` (and the bench/profile
    attribution readback). Ring-buffered by query: round detail for
    the most recent ``maxlen`` mesh queries."""

    def __init__(self, maxlen: int = 32):
        self._maxlen = maxlen
        self._flights: List[FlightRecorder] = []
        self._lock = threading.Lock()

    def add(self, flight: FlightRecorder) -> None:
        with self._lock:
            self._flights.append(flight)
            if len(self._flights) > self._maxlen:
                del self._flights[:len(self._flights) - self._maxlen]

    def clear(self) -> None:
        with self._lock:
            self._flights.clear()

    def snapshot(self) -> List[FlightRecorder]:
        with self._lock:
            return list(self._flights)

    def last(self) -> Optional[FlightRecorder]:
        with self._lock:
            return self._flights[-1] if self._flights else None

    def rows(self) -> List[tuple]:
        """``system.runtime.mesh_rounds`` rows, :data:`ROUND_COLUMNS`
        order, oldest flight first."""
        out: List[tuple] = []
        for fl in self.snapshot():
            out.extend(round_rows(fl.query_id, fl.records()))
        return out


def round_rows(query_id: str,
               records: Iterable[dict]) -> List[tuple]:
    """Render round records as :data:`ROUND_COLUMNS` tuples — the ONE
    row shape shared by the system table and the EXPLAIN ANALYZE
    section (tested row-exact in tests/test_mesh_flight.py)."""
    return [
        (query_id, r["round"], r["stage"], r["kind"],
         KIND_BUCKET.get(r["kind"], "dispatch_overhead"),
         round(r["t"], 6), round(r["wall"], 6), r["rows"], r["bytes"],
         "/".join(str(x) for x in r["loads"]) if r["loads"] else "",
         r["blocking"], r.get("rounds", 1))
        for r in records
    ]


def chrome_events(flight: FlightRecorder, pid: int = 3) -> List[dict]:
    """Chrome-trace ``X`` events for one flight — the mesh-rounds
    track merged into ``write_merged_trace`` (one tid per bucket so
    Perfetto groups the timeline by attribution)."""
    tids = {b: i for i, b in enumerate(BUCKETS)}
    events: List[dict] = [{
        "ph": "M", "pid": pid, "name": "process_name",
        "args": {"name": "mesh rounds"},
    }]
    for b, tid in tids.items():
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": b}})
    for r in flight.records():
        bucket = KIND_BUCKET.get(r["kind"], "dispatch_overhead")
        events.append({
            "ph": "X", "pid": pid, "tid": tids[bucket],
            "ts": r["t"] * 1e6, "dur": max(r["wall"], 1e-7) * 1e6,
            "name": f"{r['kind']}#{r['round']}",
            "args": {"stage": r["stage"], "rows": r["rows"],
                     "bytes": r["bytes"],
                     "loads": list(r["loads"] or ())},
        })
    return events


def history_fields(attribution: Optional[dict]) -> dict:
    """Query-history fields (obs/history.py RECORD_COLUMNS tail +
    ``system.runtime.completed_queries``) from one attribution; empty
    when the query never flew."""
    if not attribution:
        return {}
    return {
        "mesh_rounds": int(attribution["rounds"]),
        "mesh_dominant_bucket": attribution["dominant_bucket"],
        "mesh_overhead_ms": round(
            attribution["overhead_s"] * 1e3, 3),
        "mesh_buckets": json.dumps(attribution["buckets"],
                                   sort_keys=True),
    }


_SEQ = itertools.count(1)


def next_seq() -> int:
    """Fallback flight ids (``mesh_000001``) for executions outside a
    traced query span."""
    return next(_SEQ)


#: process-wide finished-flight log
FLIGHTS = FlightLog()

#: the active recorder for this execution context (None = mesh flight
#: off or not on the mesh path); set by exec/local.py around
#: execute_plan and read by the distributed executor + scan cache
CURRENT_FLIGHT: "contextvars.ContextVar[Optional[FlightRecorder]]" = \
    contextvars.ContextVar("presto_tpu_mesh_flight", default=None)


def current_flight() -> Optional[FlightRecorder]:
    return CURRENT_FLIGHT.get()

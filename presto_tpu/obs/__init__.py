"""Observability: span tracing + process-wide metrics.

The reproduction's counterpart of the reference's observability stack —
OperatorStats/QueryInfo over REST, event/SplitMonitor.java, and the JMX
connector that turns engine metrics into SQL tables (reference
presto-main/.../connector/jmx/) — reshaped for a device runtime:

- ``obs.trace``   context-propagated spans (query -> stage -> task ->
                  operator -> device-sync/compile) with a Chrome-trace
                  (Perfetto) JSON exporter and wire-carriable span
                  context for distributed stitching;
- ``obs.metrics`` process-wide counters/gauges/histograms fed by direct
                  instrumentation and by an EventListenerManager sink,
                  queryable as ``system.runtime.metrics``.

Both are always importable and safe when idle: the tracer is OFF by
default (a disabled ``span()`` returns a shared no-op and records
nothing), and metric updates are single dict/number operations.
"""
from .trace import TRACER, Span, chrome_trace, write_chrome_trace  # noqa: F401
from .metrics import REGISTRY, TASKS, attach_event_listeners  # noqa: F401

"""Observability: span tracing + process-wide metrics.

The reproduction's counterpart of the reference's observability stack —
OperatorStats/QueryInfo over REST, event/SplitMonitor.java, and the JMX
connector that turns engine metrics into SQL tables (reference
presto-main/.../connector/jmx/) — reshaped for a device runtime:

- ``obs.trace``   context-propagated spans (query -> stage -> task ->
                  operator -> device-sync/compile) with a Chrome-trace
                  (Perfetto) JSON exporter and wire-carriable span
                  context for distributed stitching;
- ``obs.metrics`` process-wide counters/gauges/histograms fed by direct
                  instrumentation and by an EventListenerManager sink,
                  queryable as ``system.runtime.metrics``;
- ``obs.exposition`` Prometheus/OpenMetrics text rendering of the
                  registry — the ``GET /v1/metrics`` scrape surface on
                  workers and the coordinator;
- ``obs.history`` bounded persistent query history (+ optional JSONL
                  sink with size-capped rotation), queryable as
                  ``system.runtime.{completed_queries,operator_stats}``;
- ``obs.log``     structured JSON-lines logging correlated by
                  query/task/trace ids from the span context;
- ``obs.profiler`` device profiling & cost attribution: per-executable
                  compile/FLOPs/HBM introspection
                  (``system.runtime.executables``), per-operator
                  device-time attribution under the ``profile`` session
                  property, HBM telemetry sampling, and host+device
                  Chrome-trace merging for ``--profile-out``.

Everything is always importable and safe when idle: the tracer is OFF
by default (a disabled ``span()`` returns a shared no-op and records
nothing), the logger is off by default, and metric updates are single
dict/number operations.
"""
from .trace import TRACER, Span, chrome_trace, write_chrome_trace  # noqa: F401
from .metrics import (  # noqa: F401
    NODES, REGISTRY, TASKS, attach_event_listeners,
)
from .exposition import parse_exposition, render_exposition  # noqa: F401
from .flight import FLIGHTS, FlightRecorder, current_flight  # noqa: F401
from .history import HISTORY, attach_history  # noqa: F401
from .log import LOG  # noqa: F401
from .profiler import EXECUTABLES, profiled, sample_hbm  # noqa: F401

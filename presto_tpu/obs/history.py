"""Persistent query history: bounded ring + optional JSONL sink.

The role of the reference's completed-query history (QueryMonitor's
QueryCompletedEvent payload retained past process queries, surfaced as
``system.runtime.completed_queries``): every finished or failed query —
local or cluster — leaves one final record carrying the SQL text, a
plan summary, wall/cpu/device-sync time, per-operator rows/bytes, peak
memory, and the error, fed through ``events.EventListenerManager`` so
both executors publish the same way.

The ring is bounded (records die with the process unless a JSONL sink
is configured with ``HISTORY.configure(sink_path=...)`` / the CLI's
``--history-out``); ``slow_threshold_s`` additionally emits the full
record through the structured logger (``--slow-query-log``).

The sink itself is bounded too: a long-lived coordinator must not grow
one JSONL file forever, so when the file passes ``max_sink_bytes``
(default 64 MiB) it rotates to ``<path>.1`` — one generation kept, the
previous ``.1``'s records dropped and counted in
``history_records_dropped_total`` so the loss is observable, never
silent.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

#: flattened columns of system.runtime.completed_queries, in order.
#: The mesh_* tail is the flight recorder's attribution summary
#: (obs/flight.history_fields) — NULL/zero for queries that never ran
#: on the mesh path.
RECORD_COLUMNS = (
    "query_id", "state", "user", "query", "error", "error_code",
    "create_time", "elapsed_ms", "cpu_ms", "device_sync_ms",
    "planning_ms", "peak_memory_bytes", "rows", "mode", "plan_summary",
    "retries", "mesh_rounds", "mesh_dominant_bucket",
    "mesh_overhead_ms", "mesh_buckets")


class QueryHistory:
    """Bounded store of final per-query records (dicts)."""

    def __init__(self, max_records: int = 1000):
        from .._devtools.lockcheck import checked_lock
        self._ring: deque = deque(maxlen=max_records)
        self._lock = checked_lock("history.ring")
        self.sink_path: Optional[str] = None
        self.slow_threshold_s: Optional[float] = None
        #: rotate the sink when it passes this size (0/None = unbounded,
        #: the pre-rotation behaviour, for tests that diff whole files)
        self.max_sink_bytes: Optional[int] = 64 << 20
        self._sink_lock = checked_lock("history.sink")
        # records written to the current sink file / living in the .1
        # generation — the .1 count is what one more rotation drops
        self._sink_records = 0
        self._rotated_records = 0

    def configure(self, sink_path: Optional[str] = None,
                  slow_threshold_s: Optional[float] = None,
                  max_sink_bytes: Optional[int] = None) -> None:
        # the whole reconfiguration happens under the sink lock: a
        # concurrent add() must never observe a half-switched sink
        # (new path with the old generation's record counts)
        with self._sink_lock:
            if sink_path is not None:
                self.sink_path = sink_path
                # resuming onto files a previous process wrote: seed the
                # record counts from what's on disk, so the FIRST
                # rotation after a restart still attributes the dropped
                # generation correctly (one line scan at configure time,
                # never per add)
                self._sink_records = self._count_lines(sink_path)
                self._rotated_records = self._count_lines(
                    sink_path + ".1")
            if slow_threshold_s is not None:
                self.slow_threshold_s = slow_threshold_s
            if max_sink_bytes is not None:
                self.max_sink_bytes = int(max_sink_bytes) or None

    @staticmethod
    def _count_lines(path: str) -> int:
        try:
            with open(path, "rb") as f:
                return sum(chunk.count(b"\n")
                           for chunk in iter(lambda: f.read(1 << 20),
                                             b""))
        except OSError:
            return 0

    def add(self, record: Dict) -> None:
        with self._lock:
            self._ring.append(record)
        if self.sink_path:
            try:
                with self._sink_lock:
                    with open(self.sink_path, "a") as f:
                        f.write(json.dumps(record, default=str) + "\n")
                        size = f.tell()
                    self._sink_records += 1
                    if self.max_sink_bytes \
                            and size >= self.max_sink_bytes:
                        self._rotate()
            except Exception:   # history must not break queries
                pass
        thr = self.slow_threshold_s
        if thr is not None \
                and float(record.get("elapsed_ms") or 0.0) >= thr * 1e3:
            from .log import LOG
            LOG.log("slow_query", **record)

    def _rotate(self) -> None:
        """Current sink becomes ``<path>.1`` (replacing — and thereby
        dropping — the previous generation); appends continue into a
        fresh file. Called with the sink lock held."""
        dropped = self._rotated_records
        os.replace(self.sink_path, self.sink_path + ".1")
        self._rotated_records = self._sink_records
        self._sink_records = 0
        if dropped:
            from .metrics import REGISTRY
            REGISTRY.counter("history_records_dropped_total").inc(dropped)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


#: the process-wide history store
HISTORY = QueryHistory()


def attach_history(events, history: Optional[QueryHistory] = None) -> None:
    """Register a query-completion listener that lands every query's
    final record in the history store. The runner attaches the rich
    payload on the event (``QueryCompletedEvent.history``); events
    without one (foreign publishers) still get a minimal record from
    the event fields."""
    h = history if history is not None else HISTORY

    def on_query_completed(ev) -> None:
        rec = dict(getattr(ev, "history", None) or {})
        rec.setdefault("query_id", ev.query_id)
        rec.setdefault("query", ev.query)
        rec.setdefault("user", ev.user)
        rec.setdefault("state", ev.state)
        rec.setdefault("error", ev.error)
        rec.setdefault("elapsed_ms", round(ev.elapsed_ms, 3))
        rec.setdefault("create_time", ev.create_time)
        rec.setdefault("mode", "local")
        # task retries this query survived (cluster fault tolerance,
        # exec/cluster.py); local queries have no retry layer -> 0
        rec.setdefault("retries", 0)
        h.add(rec)

    events.register(on_query_completed)

"""Parameter-generic plan templates: one optimized plan (and one warm
set of jit executables) for a whole fleet of bindings.

PR 8's plan cache keys on the BOUND statement, so a dashboard fleet
issuing ``EXECUTE dash USING 1001``, ``USING 1002``, ... fingerprints
every binding separately: N plans, N optimizer passes and — because
literals bake into kernels as trace-time constants — N jit compiles.
This module fingerprints the statement's parameterized SHAPE instead:

- :func:`parameterize` hole-punches eligible literals out of the AST.
  The **template** form replaces each with a value-free
  ``ast.TypedParameter`` (position + type kind) and is only ever
  hashed; the **marked** form replaces each with a ``Slot*Literal``
  that carries the value AND a binding slot — it plans through the
  normal analyzer/optimizer, except slot literals lower to runtime
  ``ir.Param`` nodes (traced scalars) instead of baked constants.
- eligibility is conservative: BIGINT / DOUBLE / short-DECIMAL / DATE
  literals appearing as operands of comparison / BETWEEN / IN-list /
  boolean / arithmetic nodes inside WHERE, HAVING, or join ON
  predicates. Everything else (LIMIT counts, GROUP BY ordinals,
  function arguments with static contracts, LIKE patterns, string
  literals whose dictionary tables build at trace time, VALUES rows)
  stays baked and is part of the template key.
- **guards**: an optimizer decision that CONSULTS a parameter's value
  (scan-pushdown bound extraction — which seeds key-bounds gates,
  stats estimates and join strategy downstream) records an equality
  guard via expr/params.consult. A template hit first checks its
  guards against the new binding; a flipped guard falls back to the
  per-binding fingerprint path (the PR 8 cache), observable as
  ``plan_template_cache_guard_fallback_total``.

Substrates that trace values as constants (remote cluster fragments,
the SPMD mesh executor, the fused join pipeline) materialize bindings
with expr/params.bind_plan / skip fusion instead of sharing the traced
executable — row-exactness first.

Session knob: ``plan_template_cache`` (default false; the serving
plane turns it on).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from ..exec.failpoints import FAILPOINTS
from ..obs.metrics import REGISTRY
from ..sql import ast as A
from .plancache import (PlanCache, bound_fingerprint, cached_plan,
                        key_fragment)

_GUARD_FALLBACK = REGISTRY.counter(
    "plan_template_cache_guard_fallback_total")

#: the process-wide template cache (a second PlanCache: same LRU,
#: data-version validation, eager invalidation and write-epoch veto,
#: its own metric family and lock)
TEMPLATES = PlanCache(metrics="plan_template_cache",
                      lock_name="plancache.templates")


@dataclasses.dataclass(frozen=True)
class Template:
    """Cached payload: the parameterized plan plus its reuse guards
    ((slot, value) equality predicates recorded at build time)."""
    plan: object
    guards: Tuple[Tuple[int, Any], ...]
    n_slots: int


# -- parameterization ---------------------------------------------------------

#: predicate-context nodes the hole-punch walk recurses THROUGH;
#: entering any other node type ends eligibility (its literals bake)
_PUNCH_CONTEXTS = (A.LogicalBinary, A.Not, A.Comparison, A.Between,
                   A.InList, A.ArithmeticBinary, A.ArithmeticUnary)

_SLOT_FORMS = {
    A.LongLiteral: (A.SlotLongLiteral, lambda e: "bigint"),
    A.DoubleLiteral: (A.SlotDoubleLiteral, lambda e: "double"),
    A.DateLiteral: (A.SlotDateLiteral, lambda e: "date"),
}


def _hole(e):
    """(slot_cls, kind) when ``e`` is an eligible literal, else None.
    Exact-type match: a literal's KIND is part of the template key, so
    ``x > 5`` and ``x > 5.0`` never share a template."""
    form = _SLOT_FORMS.get(type(e))
    if form is not None:
        return form[0], form[1](e)
    if type(e) is A.DecimalLiteral:
        from ..sql.analyzer import literal_type
        t = literal_type(e)
        if t.is_long:        # >18 digits: 2-limb storage, keep baked
            return None
        return A.SlotDecimalLiteral, t.display()
    return None


def parameterize(stmt):
    """(template_stmt, marked_stmt, values) — values is {slot: python
    value}; empty when the statement has no eligible literals (the
    caller then uses the plain bound-fingerprint cache)."""
    values: Dict[int, Any] = {}

    def walk(n, in_pred: bool):
        if in_pred:
            hole = _hole(n)
            if hole is not None:
                slot_cls, kind = hole
                slot = len(values)
                values[slot] = n.value
                return (A.TypedParameter(index=slot, kind=kind),
                        slot_cls(value=n.value, slot=slot))
        if isinstance(n, A.QuerySpecification):
            return _rebuild(n, lambda f, v: walk(
                v, f in ("where", "having")))
        if isinstance(n, A.Join):
            return _rebuild(n, lambda f, v: walk(
                v, f == "condition"))
        if isinstance(n, _PUNCH_CONTEXTS):
            return _rebuild(n, lambda f, v: walk(v, in_pred))
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            return _rebuild(n, lambda f, v: walk(v, False))
        if isinstance(n, tuple):
            pairs = [walk(x, in_pred) for x in n]
            return (tuple(p[0] for p in pairs),
                    tuple(p[1] for p in pairs))
        return n, n

    def _rebuild(n, child_walk):
        t_changes, m_changes = {}, {}
        for f in dataclasses.fields(n):
            v = getattr(n, f.name)
            if isinstance(v, (tuple,)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, type)):
                tv, mv = child_walk(f.name, v)
                if tv is not v:
                    t_changes[f.name] = tv
                if mv is not v:
                    m_changes[f.name] = mv
        t = dataclasses.replace(n, **t_changes) if t_changes else n
        m = dataclasses.replace(n, **m_changes) if m_changes else n
        return t, m

    template, marked = walk(stmt, False)
    return template, marked, values


# -- lookup / build -----------------------------------------------------------

# parse_cached returns the SAME AST object for a repeated statement
# text, so the hole-punch walk memoizes by AST identity: the serving
# steady state pays one dict probe instead of an O(tree) rebuild per
# query (plancache.IdentMemo pins the statement against id() reuse).
from .plancache import IdentMemo  # noqa: E402

_memo = IdentMemo(lock_name="template.parameterize")


def parameterize_cached(stmt):
    return _memo.get(stmt, parameterize)


def template_plan(stmt, session, user: str = "", secured: bool = False):
    """(plan, bindings, bound_key) for a SELECT statement under the
    template cache. ``bindings`` is the slot->value map to execute the
    (possibly parameterized) plan with — None when the plan came from
    the per-binding path and has no Params. ``bound_key`` is the full
    bound-statement fingerprint (the result cache keys on it)."""
    from ..expr import params as P
    from ..planner.optimizer import optimize
    from ..planner.planner import plan_query

    # one session-slice walk for both keys (bound + template)
    frag = key_fragment(session, user=user, secured=secured)
    bound_key = bound_fingerprint(stmt, session, user=user,
                                  secured=secured, fragment=frag)
    template_stmt, marked_stmt, values = parameterize_cached(stmt)
    if not values:
        plan = cached_plan(stmt, session, user=user, secured=secured)
        return plan, None, bound_key
    tkey = bound_fingerprint(template_stmt, session, user=user,
                             secured=secured, fragment=frag)
    entry = TEMPLATES.get(tkey)
    if isinstance(entry, Template):
        if len(values) == entry.n_slots and all(
                values.get(slot) == v for slot, v in entry.guards):
            return entry.plan, dict(values), bound_key
        # an optimization decision was keyed on a literal this binding
        # changed (or the shape re-punched differently): the template
        # plan would be wrong/stale for it — per-binding fingerprint
        _GUARD_FALLBACK.inc()
        plan = cached_plan(stmt, session, user=user, secured=secured)
        return plan, None, bound_key
    # miss: build the template from the marked statement, recording
    # every value consultation as a reuse guard. The building query
    # executes the parameterized plan itself (same kernels later hits
    # will dispatch), bound to its own literals.
    epoch = TEMPLATES.epoch()
    FAILPOINTS.hit("plancache.plan", key=tkey.hex()[:12])
    with P.recording_guards() as guards:
        plan = optimize(plan_query(marked_stmt, session), session)
    payload = Template(plan=plan,
                       guards=tuple(sorted(dict(guards).items())),
                       n_slots=len(values))
    TEMPLATES.put(tkey, plan, session, epoch=epoch, payload=payload)
    return plan, dict(values), bound_key


# eager write invalidation, same path as the bound-plan cache
from ..connectors import spi  # noqa: E402


def _on_write(conn, table) -> None:
    TEMPLATES.note_write()
    TEMPLATES.invalidate(conn, table)


spi.on_data_change(_on_write)

"""Versioned result/subplan cache with incremental maintenance.

Materialized-view semantics without the DDL (ROADMAP item 3; "Efficient
Tabular Data Preprocessing of ML Pipelines" is the exemplar for caching
whole preprocessing-stage outputs): a standing query — dashboard
refresh, feature recompute — keyed by its full bound-statement
fingerprint serves its stored rows as long as every scanned table's
connector ``data_version`` still matches, and when only some SPLITS of
one table changed, recomputes just the changed-split partial and merges.

Three outcomes per lookup:

- **hit** — every dep's current ``data_version`` equals the stamp the
  entry recorded at insert. Serve the stored host rows; zero planning,
  zero device work.
- **partial** — exactly one dep drifted, its connector attests
  per-file versions (filebase-style ``(seq, ((relpath, mtime), ...))``
  tokens), the drift is APPEND-ONLY (every old file unchanged, new
  files added), and the plan qualified for incremental maintenance at
  insert time. The engine re-runs the plan's aggregation subtree (the
  auto-designated *subplan*) restricted to the new splits only, merges
  the delta into the cached subplan rows (distributive merge: sum/count
  add, min/max extremize), replays the merged rows through the plan
  suffix via a ValuesNode, and re-stamps the entry.
- **miss** — anything else (rewritten/removed files, >1 drifted dep,
  non-distributive plan). The query runs cold; an eligible result
  inserts with a write-epoch veto mirroring the plan cache's TOCTOU
  fix: deps are stamped BEFORE execution, and a connector write
  notifying mid-run bumps the epoch and refuses the insert.

Incremental eligibility (computed once at insert):

- single-child chain from the root down to ONE AggregationNode
  (Output/Project/Filter/Sort/TopN/Limit/Distinct suffix — the suffix
  re-executes over the merged subplan rows, so HAVING/ORDER/LIMIT are
  all fine);
- the aggregation is ``step == "single"`` with distributive functions
  only (sum/count/min/max, no DISTINCT);
- below it only Filter/Project over EXACTLY ONE TableScanNode, whose
  connector exposes per-file versions, and no other scan anywhere in
  the plan (init plans included);
- the subplan result fits one batch (``MAX_SUBPLAN_ROWS``).

Memory: entries account host-row bytes against a dedicated
``memory.QueryMemoryPool`` (``result-cache.max-bytes`` config key,
default 256 MiB) with LRU eviction. Eager invalidation rides
``spi.on_data_change`` like every other cache in the engine.

Metrics: ``result_cache_{hit,miss,partial,invalidated,evicted}_total``
+ ``result_cache_resident_bytes``. Session knob: ``result_cache``
(default false; the serving plane turns it on).
"""
from __future__ import annotations

import dataclasses
import sys
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .._devtools.lockcheck import checked_lock, guarded_by
from ..exec.failpoints import FAILPOINTS
from ..memory import QueryMemoryPool
from ..obs.metrics import REGISTRY
from .plancache import PlanCache, _freeze

_HITS = REGISTRY.counter("result_cache_hit_total")
_MISSES = REGISTRY.counter("result_cache_miss_total")
_PARTIAL = REGISTRY.counter("result_cache_partial_total")
_INVALIDATED = REGISTRY.counter("result_cache_invalidated_total")
_EVICTED = REGISTRY.counter("result_cache_evicted_total")
_RESIDENT = REGISTRY.gauge("result_cache_resident_bytes")

DEFAULT_MAX_BYTES = 256 << 20
#: merged subplan rows replay through a single ValuesNode batch
MAX_SUBPLAN_ROWS = 1 << 17

_DISTRIBUTIVE = frozenset(["sum", "count", "count_star", "min", "max"])


def _rows_bytes(rows) -> int:
    """Rough host footprint of a row list (python tuples of scalars)."""
    total = sys.getsizeof(rows) if rows is not None else 0
    for r in rows or ():
        total += sys.getsizeof(r)
        for v in r:
            total += sys.getsizeof(v)
    return total


@dataclasses.dataclass
class IncrementalSpec:
    """How to maintain one entry incrementally (captured at insert)."""
    #: the aggregation subtree (the designated subplan) — plan node
    agg: object
    #: dep index (into entry.deps) of the single file-versioned table
    dep_index: int
    #: catalog / table the delta scan restriction applies to
    catalog: str
    table: str
    #: number of leading group-key columns in the subplan rows
    n_keys: int
    #: (column index, fn) for each aggregate column of the subplan rows
    agg_cols: Tuple[Tuple[int, str], ...]


class _Entry:
    __slots__ = ("rows", "names", "types", "deps", "bytes", "ctx",
                 "subplan_rows", "spec", "plan")

    def __init__(self, rows, names, types, deps, ctx,
                 subplan_rows=None, spec=None, plan=None):
        self.rows = rows
        self.names = names
        self.types = types
        #: [(connector weakref, catalog, table, frozen data version)]
        self.deps: List[Tuple] = deps
        self.ctx = ctx                     # pool memory context
        self.bytes = 0
        self.subplan_rows = subplan_rows   # agg-level rows (incremental)
        self.spec: Optional[IncrementalSpec] = spec
        self.plan = plan                   # the optimized plan (suffix replay)


@dataclasses.dataclass
class PartialHit:
    """A lookup that can be served by delta recompute + merge. Base
    state is SNAPSHOTTED at lookup: two concurrent partial hits on one
    entry each merge delta into the same base (never into the other's
    merged result), and ``update`` rejects the second re-stamp via the
    ``base_deps`` compare — the delta can never double-apply."""
    entry: _Entry
    key: bytes
    new_files: frozenset          # relpaths to restrict the delta scan to
    fresh_deps: List[Tuple]       # deps to re-stamp the entry with
    epoch: int                    # veto epoch captured at lookup
    base_deps: List[Tuple]        # dep stamps the snapshot was valid for
    base_subplan: object          # subplan rows at lookup (never mutated)
    plan: object
    spec: "IncrementalSpec"


class ResultCache:
    """Process-wide LRU of final (and designated-subplan) query results
    keyed by bound-statement fingerprint + connector data versions."""

    #: guarded-field contracts (lockcheck): entry map and write epoch
    #: only under the cache lock
    _entries = guarded_by(attr="_lock")
    _epoch = guarded_by(attr="_lock")

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._lock = checked_lock("resultcache.entries")
        self._epoch = 0
        self.pool = QueryMemoryPool(max_bytes)

    # -- config ---------------------------------------------------------------
    def set_limit(self, max_bytes: int) -> None:
        with self._lock:
            self.pool.limit = max_bytes
            self._shrink_locked()

    # -- write epoch ----------------------------------------------------------
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def note_write(self) -> None:
        with self._lock:
            self._epoch += 1

    # -- lookup ---------------------------------------------------------------
    def get(self, key: bytes):
        """("hit", QueryResult-parts) | ("partial", PartialHit) |
        ("miss", None). Dep revalidation runs OUTSIDE the lock (filebase
        versions stat files)."""
        with self._lock:
            e = self._entries.get(key)
            epoch = self._epoch
            if e is not None:
                # consistent snapshot: a concurrent partial update
                # replaces deps/subplan_rows wholesale (never mutates),
                # so these references stay internally coherent
                base_deps = list(e.deps)
                base_subplan = e.subplan_rows
        if e is None:
            _MISSES.inc()
            return "miss", None
        fresh: List[Tuple] = []
        drifted: List[int] = []
        for i, dep in enumerate(base_deps):
            conn_ref, catalog, table, version = dep
            conn = conn_ref()
            ver_fn = getattr(conn, "data_version", None) if conn else None
            now = _freeze(ver_fn(table)) if ver_fn else None
            fresh.append((conn_ref, catalog, table, now))
            if now is None or now != version:
                drifted.append(i)
        if not drifted:
            with self._lock:
                if self._entries.get(key) is e:
                    self._entries.move_to_end(key)
            _HITS.inc()
            return "hit", e
        if (e.spec is not None and drifted == [e.spec.dep_index]):
            old_v = base_deps[e.spec.dep_index][3]
            new_v = fresh[e.spec.dep_index][3]
            added = _appended_files(old_v, new_v)
            if added is not None:
                return "partial", PartialHit(
                    entry=e, key=key, new_files=frozenset(added),
                    fresh_deps=fresh, epoch=epoch,
                    base_deps=base_deps, base_subplan=base_subplan,
                    plan=e.plan, spec=e.spec)
        # rewritten / removed files, or a non-incremental entry: drop
        self._drop(key, e)
        _MISSES.inc()
        return "miss", None

    def probe(self, key: bytes):
        """Metric-silent, LRU-silent peek for EXPLAIN ANALYZE: (rows,
        bytes, incremental?) of a resident entry, else None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            return (len(e.rows), e.bytes, e.spec is not None)

    def stats(self):
        with self._lock:
            return {"entries": len(self._entries),
                    "resident_bytes": self.pool.reserved}

    def _drop(self, key: bytes, e: Optional[_Entry] = None) -> None:
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None and (e is None or cur is e):
                del self._entries[key]
                cur.ctx.close()
                _INVALIDATED.inc()
                _RESIDENT.set(self.pool.reserved)

    # -- insert / update ------------------------------------------------------
    def put(self, key: bytes, result, deps, epoch: int,
            subplan_rows=None, spec: Optional[IncrementalSpec] = None,
            plan=None) -> bool:
        """Insert a cold result. ``deps`` were stamped BEFORE execution;
        ``epoch`` too — a connector write notifying mid-run bumps the
        epoch and vetoes the insert (the result may straddle versions)."""
        if deps is None:
            return False
        size = _rows_bytes(result.rows) + _rows_bytes(subplan_rows) + 1024
        with self._lock:
            if epoch != self._epoch:
                return False
            if key in self._entries:
                return True
            if size > self.pool.limit:
                return False
            ctx = self.pool.context(f"result:{key.hex()[:12]}")
            e = _Entry(list(result.rows), list(result.names),
                       list(result.types), list(deps), ctx,
                       subplan_rows=subplan_rows, spec=spec, plan=plan)
            self._entries[key] = e
            self._account_locked(e, size)
            return True

    def update(self, ph: PartialHit, result, subplan_rows) -> bool:
        """Re-stamp a partially-recomputed entry with the merged rows
        and the fresh dep versions (veto on mid-delta writes, and on a
        concurrent partial that re-stamped first — the merge was
        computed against ``base_deps``' snapshot and must not overwrite
        a newer state it didn't incorporate)."""
        size = (_rows_bytes(result.rows) + _rows_bytes(subplan_rows)
                + 1024)
        with self._lock:
            if ph.epoch != self._epoch:
                return False
            e = self._entries.get(ph.key)
            if e is not ph.entry:
                return False
            if e.deps != ph.base_deps:
                return False       # a concurrent partial won the race
            if size > self.pool.limit:
                # outgrew the cache: serve this query, drop the entry
                del self._entries[ph.key]
                e.ctx.close()
                _EVICTED.inc()
                _RESIDENT.set(self.pool.reserved)
                return False
            e.rows = list(result.rows)
            if subplan_rows is not None \
                    and len(subplan_rows) > MAX_SUBPLAN_ROWS:
                # outgrew the single-batch replay cap the insert path
                # enforces: keep serving full hits, stop maintaining
                e.subplan_rows = None
                e.spec = None
            else:
                e.subplan_rows = subplan_rows
            e.deps = list(ph.fresh_deps)
            self._account_locked(e, size)
            return True

    def _account_locked(self, e: _Entry, size: int) -> None:
        if e.bytes:
            e.ctx.release_all()
        e.bytes = size
        self._shrink_locked(keep=e)
        self.pool.reserve(size, e.ctx)
        _RESIDENT.set(self.pool.reserved)

    def _shrink_locked(self, keep: Optional[_Entry] = None) -> None:
        need = (keep.bytes if keep is not None else 0)
        while self._entries and \
                self.pool.reserved + need > self.pool.limit:
            victim_key = next((k for k, v in self._entries.items()
                               if v is not keep), None)
            if victim_key is None:
                break
            victim = self._entries.pop(victim_key)
            victim.ctx.close()
            _EVICTED.inc()
        _RESIDENT.set(self.pool.reserved)

    # -- invalidation ---------------------------------------------------------
    def invalidate(self, conn=None, table: Optional[str] = None) -> None:
        """Eager write invalidation (spi.notify_data_change): drop every
        entry depending on the written table — EXCEPT incremental
        entries whose changed table supports append-only maintenance;
        those stay resident and resolve hit/partial/miss on next lookup
        against the fresh version."""
        with self._lock:
            victims = []
            for key, e in self._entries.items():
                for i, (conn_ref, _cat, tab, _ver) in enumerate(e.deps):
                    ref = conn_ref()
                    if ref is None:
                        victims.append(key)
                        break
                    if conn is not None and ref is not conn:
                        continue
                    if table is not None and tab != table:
                        continue
                    if e.spec is not None and i == e.spec.dep_index:
                        continue       # maintainable: keep for partial
                    victims.append(key)
                    break
            for key in victims:
                e = self._entries.pop(key)
                e.ctx.close()
            if victims:
                _INVALIDATED.inc(len(victims))
                _RESIDENT.set(self.pool.reserved)

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                e.ctx.close()
            self._entries.clear()
            _RESIDENT.set(self.pool.reserved)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def _appended_files(old_version, new_version):
    """Relpaths added between two filebase-style ``(seq, ((relpath,
    mtime), ...))`` version tokens, or None when the drift is not
    append-only (missing/rewritten files, foreign token shape)."""
    def files_of(v):
        if (isinstance(v, tuple) and len(v) == 2
                and isinstance(v[1], tuple)):
            try:
                return dict(v[1])
            except (TypeError, ValueError):
                return None
        return None

    old_f, new_f = files_of(old_version), files_of(new_version)
    if old_f is None or new_f is None:
        return None
    for name, mtime in old_f.items():
        if new_f.get(name) != mtime:
            return None                # rewritten or removed: full miss
    added = [name for name in new_f if name not in old_f]
    return added if added else None


# -- plan analysis ------------------------------------------------------------

_SUFFIX_NODES = None


def _suffix_types():
    global _SUFFIX_NODES
    if _SUFFIX_NODES is None:
        from ..planner.plan import (
            DistinctNode, FilterNode, LimitNode, OutputNode, ProjectNode,
            SortNode, TopNNode,
        )
        _SUFFIX_NODES = (OutputNode, ProjectNode, FilterNode, SortNode,
                         TopNNode, LimitNode, DistinctNode)
    return _SUFFIX_NODES


def incremental_spec(plan, session, deps) -> Optional[IncrementalSpec]:
    """IncrementalSpec when ``plan`` qualifies for append-only
    maintenance, else None. See module docstring for the contract."""
    from ..planner.plan import (
        AggregationNode, FilterNode, ProjectNode, TableScanNode,
    )
    if plan.init_plans:
        return None
    node = plan.root
    while isinstance(node, _suffix_types()) and node.children:
        if isinstance(node, AggregationNode):
            break
        if len(node.children) != 1:
            return None
        node = node.children[0]
        if isinstance(node, AggregationNode):
            break
    if not isinstance(node, AggregationNode):
        return None
    agg = node
    if agg.step != "single":
        return None
    for a in agg.aggs:
        if a.fn not in _DISTRIBUTIVE or a.distinct:
            return None
    # below the agg: Filter/Project over exactly one scan
    scans = []

    def walk(n) -> bool:
        if isinstance(n, TableScanNode):
            scans.append(n)
            return True
        if isinstance(n, (FilterNode, ProjectNode)):
            return all(walk(c) for c in n.children)
        return False

    if not walk(agg.child) or len(scans) != 1:
        return None
    scan = scans[0]
    dep_index = None
    for i, (_ref, cat, tab, ver) in enumerate(deps):
        if cat == scan.catalog and tab == scan.table.table:
            dep_index = i
            break
    if dep_index is None:
        return None
    if _appended_file_capable(deps[dep_index][3]) is None:
        return None
    conn = session.catalogs.get(scan.catalog)
    if not hasattr(conn, "root"):       # split restriction needs relpaths
        return None
    nk = len(agg.group_indices)
    agg_cols = tuple((nk + i, a.fn) for i, a in enumerate(agg.aggs))
    return IncrementalSpec(agg=agg, dep_index=dep_index,
                           catalog=scan.catalog, table=scan.table.table,
                           n_keys=nk, agg_cols=agg_cols)


def _appended_file_capable(version):
    """The per-file detail of a frozen version token, or None."""
    if (isinstance(version, tuple) and len(version) == 2
            and isinstance(version[1], tuple)):
        return version[1]
    return None


# -- delta recompute ----------------------------------------------------------

def subplan_result(plan, spec: IncrementalSpec, session,
                   rows_per_batch: int, cancel_event=None,
                   split_restrict=None):
    """Run the designated subplan (the aggregation subtree) —
    optionally restricted to a split subset — and return its rows."""
    from ..planner.planner import LogicalPlan
    from ..planner.plan import OutputNode
    from ..exec.local import execute_plan
    sub = LogicalPlan(root=OutputNode(child=spec.agg,
                                      fields=spec.agg.fields),
                      init_plans=[])
    return execute_plan(sub, session, rows_per_batch,
                        cancel_event=cancel_event,
                        split_restrict=split_restrict).rows


def merge_subplan_rows(spec: IncrementalSpec, base_rows, delta_rows):
    """Distributive merge of two subplan row sets keyed by the group
    columns. Append-only deltas make sum/count additive and min/max
    monotone; a NULL aggregate means 'no rows contributed' and yields
    to the other side."""
    def combine(fn, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if fn in ("sum", "count", "count_star"):
            return a + b
        if fn == "min":
            return a if a <= b else b
        return a if a >= b else b

    nk = spec.n_keys
    merged = OrderedDict()
    for row in list(base_rows) + list(delta_rows):
        k = tuple(row[:nk])
        cur = merged.get(k)
        if cur is None:
            merged[k] = list(row)
        else:
            for idx, fn in spec.agg_cols:
                cur[idx] = combine(fn, cur[idx], row[idx])
    return [tuple(r) for r in merged.values()]


def replay_suffix(plan, spec: IncrementalSpec, merged_rows, session,
                  rows_per_batch: int, cancel_event=None):
    """Execute the plan's suffix over the merged subplan rows: the
    aggregation subtree is swapped for a ValuesNode replaying them."""
    import dataclasses as _dc
    from ..planner.plan import ValuesNode
    from ..planner.planner import LogicalPlan
    from ..exec.local import execute_plan
    source = ValuesNode(fields=spec.agg.fields,
                        rows=tuple(tuple(r) for r in merged_rows))

    def swap(n):
        if n is spec.agg:
            return source
        changes = {}
        for f in _dc.fields(n):
            v = getattr(n, f.name)
            if v is spec.agg:
                changes[f.name] = source
            elif isinstance(v, tuple) and any(x is spec.agg for x in v):
                changes[f.name] = tuple(
                    source if x is spec.agg else x for x in v)
            elif hasattr(v, "children") and hasattr(v, "fields") \
                    and _dc.is_dataclass(v) and not isinstance(v, type):
                nv = swap(v)
                if nv is not v:
                    changes[f.name] = nv
        return _dc.replace(n, **changes) if changes else n

    suffix = LogicalPlan(root=swap(plan.root), init_plans=[])
    return execute_plan(suffix, session, rows_per_batch,
                        cancel_event=cancel_event)


def split_predicate(session, spec: IncrementalSpec, new_files):
    """Split-restriction map keeping only the new files (filebase split
    info carries the absolute path; versions use root-relative paths),
    or None when ANY current split cannot be classified old-vs-new —
    fail CLOSED: an old split kept by mistake would re-aggregate rows
    the base result already contains."""
    import os
    from ..connectors.spi import TableHandle
    conn = session.catalogs.get(spec.catalog)
    root = conn.root

    def rel_of(split):
        try:
            rel = os.path.relpath(split.info[0], root)
        except (TypeError, IndexError, ValueError):
            return None
        return rel

    try:
        handle = TableHandle(spec.catalog, "default", spec.table)
        current = conn.split_manager.splits(handle)
    except Exception:
        return None
    rels = {id(s): rel_of(s) for s in current}
    if any(r is None for r in rels.values()):
        return None

    def pred(split) -> bool:
        rel = rel_of(split)
        return rel is not None and rel in new_files

    return {(spec.catalog, spec.table): pred}


# -- runner orchestration -----------------------------------------------------

def begin(key: bytes, plan, session, rows_per_batch: int,
          cancel_event=None, stats=None):
    """One entry point for BOTH runners (LocalRunner and ClusterRunner
    must agree on keying/epoch/veto semantics): try to serve from the
    cache; on a miss return ``(None, token)`` where ``token`` carries
    the pre-execution dep/epoch stamps for :func:`commit`."""
    served = serve(key, session, rows_per_batch,
                   cancel_event=cancel_event, stats=stats)
    if served is not None:
        return served, None
    # epoch BEFORE deps (the cached_plan order): plan_deps stats every
    # filebase table, and a write landing inside that window must veto
    # the insert — deps-then-epoch would stamp pre-write versions on a
    # post-write epoch and the next lookup would double-apply the
    # "new" files its rows already contain
    epoch = RESULTS.epoch()
    FAILPOINTS.hit("resultcache.stamp", key=key.hex()[:12])
    deps = plan_deps(plan, session)
    return None, (key, plan, epoch, deps, rows_per_batch, cancel_event)


def commit(token, session, result) -> bool:
    """Insert a cold result under the stamps ``begin`` captured."""
    if token is None:
        return False
    key, plan, epoch, deps, rows_per_batch, cancel_event = token
    if deps is None:
        return False
    return store(key, plan, session, result, deps, epoch,
                 rows_per_batch, cancel_event=cancel_event)


def serve(key: bytes, session, rows_per_batch: int,
          cancel_event=None, stats=None):
    """QueryResult for a hit or partial hit, else None (the caller runs
    cold). The partial path runs the delta subplan restricted to the
    new splits, merges, replays the suffix, and re-stamps the entry —
    all on the local executor (the delta is a small restricted scan)."""
    from ..exec.local import QueryResult
    outcome, obj = RESULTS.get(key)
    if outcome == "hit":
        e = obj
        if stats is not None:
            stats.result_cache = "hit"
        return QueryResult(names=list(e.names), types=list(e.types),
                           rows=list(e.rows))
    if outcome == "partial":
        ph: PartialHit = obj
        restrict = split_predicate(session, ph.spec, ph.new_files)
        if restrict is None:
            # a split couldn't be classified as old-vs-new: fail CLOSED
            # (a kept-by-mistake old split would double-count in the
            # merge) — drop the entry and run cold
            RESULTS._drop(ph.key, ph.entry)
            _MISSES.inc()
            if stats is not None:
                stats.result_cache = "miss"
            return None
        _PARTIAL.inc()
        # the PR 12 double-apply window: a second partial hit racing
        # this delta recompute must merge against ITS OWN lookup-time
        # snapshot and lose the update() re-stamp race
        FAILPOINTS.hit("resultcache.partial", key=ph.key.hex()[:12])
        # merge against the LOOKUP-TIME snapshot: a concurrent partial
        # may re-stamp the live entry mid-flight, and merging into its
        # result would apply this delta twice
        delta = subplan_result(ph.plan, ph.spec, session, rows_per_batch,
                               cancel_event=cancel_event,
                               split_restrict=restrict)
        merged = merge_subplan_rows(ph.spec, ph.base_subplan, delta)
        out = replay_suffix(ph.plan, ph.spec, merged, session,
                            rows_per_batch, cancel_event=cancel_event)
        RESULTS.update(ph, out, merged)
        if stats is not None:
            stats.result_cache = "partial"
        return out
    if stats is not None:
        stats.result_cache = "miss"
    return None


def store(key: bytes, plan, session, result, deps, epoch: int,
          rows_per_batch: int, cancel_event=None) -> bool:
    """Insert a cold result (deps/epoch stamped BEFORE execution).
    Incremental-eligible plans additionally capture the designated
    subplan's rows — a second pass over the aggregation subtree whose
    scans replay warm out of the device scan cache; a write landing
    anywhere in this window bumps the epoch and vetoes the insert."""
    if deps is None:
        return False
    bindings = getattr(session, "param_bindings", None)
    if bindings:
        # template plans carry ir.Param nodes bound per query; the
        # CACHED plan re-executes later (partial delta + suffix replay)
        # under queries that may have NO binding scope (template guard
        # fallback) — store the materialized form
        from ..expr.params import bind_plan, has_params
        if has_params(plan):
            plan = bind_plan(plan, bindings)
    spec = incremental_spec(plan, session, deps)
    subplan_rows = None
    if spec is not None:
        try:
            subplan_rows = subplan_result(plan, spec, session,
                                          rows_per_batch,
                                          cancel_event=cancel_event)
        except Exception:
            spec, subplan_rows = None, None
        if subplan_rows is not None \
                and len(subplan_rows) > MAX_SUBPLAN_ROWS:
            spec, subplan_rows = None, None
    return RESULTS.put(key, result, deps, epoch,
                       subplan_rows=subplan_rows, spec=spec, plan=plan)


#: the process-wide cache (fingerprints embed connector identities, so
#: one cache serves every runner in the process, like the plan cache)
RESULTS = ResultCache()

from ..connectors import spi  # noqa: E402


def _on_write(conn, table) -> None:
    RESULTS.note_write()
    RESULTS.invalidate(conn, table)


spi.on_data_change(_on_write)


def plan_deps(plan, session):
    """Exec-time dep stamps for a plan (None = uncacheable)."""
    return PlanCache._plan_deps(plan, session)

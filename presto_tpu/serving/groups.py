"""Per-group execution accounting: the glue between admission control
(server/resource_groups.py) and the executor.

The reference tracks ``cachedMemoryUsageBytes`` per resource group and
refuses to start queries in a group above its ``softMemoryLimit``
(reference execution/resourcegroups/InternalResourceGroup.java
``canRunMore``/``updateMemoryUsage``); device-time fairness lives in a
separate TaskExecutor. Here both bridges meet in one per-query
:class:`QueryServingContext`:

- **memory** — the query's ``memory.QueryMemoryPool`` charges every
  device-byte reservation to the admitting group chain (under the
  manager's memory lock). A group past its ``softMemoryLimit`` queues
  new queries (``ResourceGroup.can_run_more``); a reservation pushing
  any ancestor past its ``hardMemoryLimit`` raises — the requesting
  query is killed (``resource_group_memory_kill_total``) instead of
  the whole group wedging.
- **device** — ``exec/taskexec.DeviceScheduler`` quanta are allotted
  per group (stride scheduling over ``schedulingWeight``), then per
  task within the group; the context carries the group path + weight
  so ``execute_plan`` can register its task handle under the right
  share.

``group_snapshot()`` joins every live manager's admission counters
with the scheduler's device-share ledger — the feed for
``system.runtime.resource_groups``.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional

from .._devtools.lockcheck import checked_lock
from ..memory import MemoryLimitExceeded
from ..obs.metrics import REGISTRY

_MEMORY_KILLS = REGISTRY.counter("resource_group_memory_kill_total")

#: every live ResourceGroupManager registers here (construction-time),
#: so the process-wide system.runtime.resource_groups table can reflect
#: the servers running in this process without holding them alive.
#: WeakSet mutation is not atomic (add races GC-driven discard); two
#: servers booting concurrently must not lose a registration.
_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()
_managers_lock = checked_lock("serving.managers")


def register_manager(manager) -> None:
    with _managers_lock:
        _MANAGERS.add(manager)


def live_managers() -> list:
    """Stable list of the live managers (the SLO tracker and the
    signals feed walk group trees through this)."""
    with _managers_lock:
        return list(_MANAGERS)


class QueryServingContext:
    """One admitted query's serving identity: the group it bills memory
    to and the scheduler share its device quanta draw from. Carried on
    the per-query ``Session.serving`` field; ``close()`` refunds any
    residual group memory exactly once (every protocol exit path calls
    it, so accounting cannot leak with the admission slot)."""

    def __init__(self, group):
        self.group = group
        self.group_path: str = group.path
        #: scheduler share key, scoped by the owning manager so two
        #: servers' same-named groups keep separate stride accounts
        self.scheduler_group: str = \
            f"{group.manager.scope}/{group.path}"
        self.weight: int = group.scheduling_weight
        self._net = 0
        self._closed = False

    # -- memory bridge (QueryMemoryPool.group protocol) ----------------------
    def charge(self, delta: int) -> None:
        """Bill ``delta`` device bytes (negative = release) to the
        admitting group chain. Raises MemoryLimitExceeded when a grow
        would push any ancestor past its hard limit — the pool
        propagates it and this query fails, the group survives."""
        mgr = self.group.manager
        with mgr.memory_lock:
            if self._closed:
                return
            if delta > 0:
                g = self.group
                while g is not None:
                    if g.hard_memory_limit is not None \
                            and g.memory_reserved + delta \
                            > g.hard_memory_limit:
                        _MEMORY_KILLS.inc()
                        raise MemoryLimitExceeded(
                            f"resource group {g.path!r} hard memory "
                            f"limit {g.hard_memory_limit} bytes "
                            f"exceeded (reserved {g.memory_reserved}, "
                            f"requested {delta})")
                    g = g.parent
            self._net += delta
            g = self.group
            while g is not None:
                g.memory_reserved += delta
                g = g.parent

    def close(self) -> None:
        """Refund whatever this query still has charged (idempotent) and
        wake the dispatcher — a group queued on its soft memory limit
        may become eligible the moment this query's bytes return."""
        mgr = self.group.manager
        with mgr.memory_lock:
            if self._closed:
                return
            self._closed = True
            residual, self._net = self._net, 0
            if residual:
                g = self.group
                while g is not None:
                    g.memory_reserved -= residual
                    g = g.parent
        mgr._dispatch()


def serving_context(admission) -> Optional[QueryServingContext]:
    """Context for a granted admission (None when admission control is
    not in play, e.g. direct LocalRunner use)."""
    if admission is None:
        return None
    return QueryServingContext(admission.group)


def group_snapshot() -> List[Dict]:
    """Rows for ``system.runtime.resource_groups``: every group of every
    live manager, joined with the device scheduler's per-group ledger."""
    from ..exec.taskexec import GLOBAL as scheduler
    shares = scheduler.group_shares()
    total_device = sum(s["device_seconds"] for s in shares.values()) \
        or 0.0
    out: List[Dict] = []
    with _managers_lock:
        managers = list(_MANAGERS)
    for mgr in managers:
        for info in mgr.info():
            stack = [info]
            while stack:
                g = stack.pop()
                share = shares.get(f"{mgr.scope}/{g['id']}", {})
                dev_s = float(share.get("device_seconds", 0.0))
                out.append({
                    "group": g["id"],
                    "state": g["state"],
                    "running": g["numRunning"],
                    "queued": g["numQueued"],
                    "memory_reserved_bytes": g["memoryReservedBytes"],
                    "soft_memory_limit_bytes": g["softMemoryLimitBytes"],
                    "scheduling_weight": g["schedulingWeight"],
                    "device_seconds": dev_s,
                    "device_share": (dev_s / total_device
                                     if total_device else 0.0),
                    "quanta": int(share.get("quanta", 0)),
                })
                stack.extend(g["subGroups"])
    return out

"""Multi-tenant serving plane.

The layer-3 analogue of the reference's dispatcher + resource-group
subsystem (reference presto-main/.../dispatcher/DispatchManager.java +
execution/resourcegroups/InternalResourceGroup.java), reshaped for one
shared device and steady repeated traffic:

- :mod:`presto_tpu.serving.plancache` — a compiled-plan cache keyed by
  a parameterized statement fingerprint, so a repeated or EXECUTE'd
  statement skips parse/plan/optimize entirely and lands on the
  already-compiled executables in ``ops/jitcache``;
- :mod:`presto_tpu.serving.groups` — the per-query serving context
  that bridges an admitted resource group into execution: memory
  reservations charged to the group (kill-or-queue on limits) and a
  weighted device-scheduler share (``exec/taskexec``).

``server/resource_groups.py`` stays the admission-control front;
``exec/scancache.py`` contributes shared-scan batching (concurrent
admitted queries over the same split attach to one in-flight decode).
"""
from .groups import QueryServingContext, group_snapshot  # noqa: F401
from .plancache import PLANS, PlanCache, cached_plan  # noqa: F401

"""Coordinator fleet membership: coherent caches + federated admission.

One :class:`FleetMember` rides inside each coordinator process
(server/protocol.PrestoTpuServer.enable_fleet) and makes N stateless
coordinators over one shared worker pool behave like one serving
plane (the reference's dispatcher split — SURVEY layers 2-3: scale the
front door by replicating dispatch, carry correctness in versioned
invalidation rather than shared memory):

**Cache coherence (write bumps).** Every coordinator's plan/template/
result/scan caches already subscribe to ``connectors.spi
.on_data_change`` and invalidate eagerly on LOCAL connector writes
(PR 8/12). The member adds the wire hop: a local write broadcasts a
monotonic ``(connector_id, table, data_version, write_epoch)`` bump to
every peer (``POST /v1/fleet/bump``); the receiving member resolves
``connector_id`` (the catalog name — the only cross-process-stable
connector identity) to ITS OWN connector instance and folds the bump
by calling ``spi.notify_data_change`` on it. Folding through the spi
path — never by poking caches directly — is the audited-path contract
the static checker enforces (tools/analyze/caches.py fleet clauses):
every cache's registered ``_on_write`` listener runs, each one bumping
its write epoch BEFORE dropping entries, so a remote bump racing a
local plan/template/result insert vetoes that insert exactly like a
local write would (the PR 8/12/13 epoch-before-deps contract holds
across the wire). Bumps are deduped per ``(origin, connector, table)``
by the origin's monotonic sequence; the dedupe high-water mark is
advanced only AFTER the fold so a failed fold is retried, never
silently skipped. A coordinator that misses a broadcast entirely
(peer crash, armed ``fleet.broadcast`` failpoint) still fails safe:
every cache hit revalidates its stamped ``data_version`` against the
connector before serving.

**Federated admission (heartbeats).** Resource-group limits are
per-tenant promises, not per-coordinator ones. Members exchange
per-group ``{running, queued, memory}`` counts on the heartbeat
cadence (``POST /v1/fleet/heartbeat``); the local
``ResourceGroupManager`` consults the federated view (it installs
this member as its ``federation`` provider) so ``can_run_more`` sums
remote running counts and remote memory into every limit check.
Remote snapshots older than the staleness grace (default 3 heartbeats)
are ignored — bounded staleness: a dead peer's counts expire instead
of hard-blocking the fleet, and the first grace expiry per peer counts
``coordinator_lost_total`` (a clean drain sends a final ``leaving``
heartbeat and is NOT a loss). Heartbeats also carry the per-group
``serving_*`` SLO counters; the receiver feeds them into the local
time-series store via the PR 16 federated ``record()`` path (origin-
tagged series), so any coordinator's health plane can aggregate the
fleet's per-tenant traffic.

**Failure model.** Coordinator death is a non-event: the client
(client.FleetClient) round-robins statements and retries a failed
dispatch on the next coordinator; queued queries blocked on a dead
peer's federated counts unblock after the grace; caches self-heal via
hit-time revalidation. There is no fleet consensus and no leader —
members are peers, and every message is idempotent-or-monotonic.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
import weakref
from typing import Dict, List, Optional, Tuple

from .._devtools.lockcheck import checked_lock
from ..connectors import spi
from ..exec.failpoints import FAILPOINTS, FailpointError
from ..obs.metrics import REGISTRY

_BUMP_BROADCAST = REGISTRY.counter("fleet_bump_broadcast_total")
_BUMP_DROPPED = REGISTRY.counter("fleet_bump_dropped_total")
_BUMP_FOLD = REGISTRY.counter("fleet_bump_fold_total")
_BUMP_STALE = REGISTRY.counter("fleet_bump_stale_total")
_BUMP_UNKNOWN = REGISTRY.counter("fleet_bump_unknown_catalog_total")
_PEER_POST_FAILURE = REGISTRY.counter("fleet_peer_post_failure_total")
_HEARTBEAT = REGISTRY.counter("fleet_heartbeat_total")
_HEARTBEAT_FOLD = REGISTRY.counter("fleet_heartbeat_fold_total")
_REMOTE_BLOCKED = REGISTRY.counter(
    "fleet_admission_remote_blocked_total")
_COORDINATOR_LOST = REGISTRY.counter("coordinator_lost_total")

#: serving counter families a heartbeat federates: the cumulative
#: per-group SLO feeds (quantile points are derived locally, never
#: shipped).  ``serving_latency_seconds`` is a histogram — only its
#: flattened ``.count``/``.sum`` rows are cumulative.
_SERVING_FAMILIES = ("serving_requests_total.",
                     "serving_errors_total.")
_SERVING_HIST_TAILS = (".count", ".sum")
_SERVING_HIST_PREFIX = "serving_latency_seconds."


def _is_federated_serving(name: str) -> bool:
    if name.startswith(_SERVING_FAMILIES):
        return True
    return (name.startswith(_SERVING_HIST_PREFIX)
            and name.endswith(_SERVING_HIST_TAILS))


def _post_json(url: str, doc: dict, timeout: float) -> None:
    body = json.dumps(doc).encode()
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout):
        pass


def _jsonable(v):
    """Data versions are connector-defined (tuples of (file, mtime),
    ints, ...); the bump carries a JSON-safe rendering, advisory only —
    the receiver's caches re-read their OWN connector's version at
    hit time."""
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class FleetMember:
    """One coordinator's seat in the fleet. Stateless beyond soft
    state: the bump dedupe marks and remote heartbeat snapshots both
    rebuild from the wire after a restart."""

    def __init__(self, node_id: str, self_url: str, catalogs=None,
                 resource_groups=None, discovery=None,
                 peers=(), heartbeat_s: float = 1.0,
                 staleness_grace_s: Optional[float] = None,
                 post_timeout_s: float = 5.0):
        self.node_id = node_id
        self.self_url = self_url.rstrip("/")
        self._catalogs = catalogs
        self._manager = resource_groups
        self._discovery = discovery
        self.heartbeat_s = float(heartbeat_s)
        #: bounded staleness: remote counts older than this are ignored
        #: by admission and the origin is declared lost (once)
        self.staleness_grace_s = (3.0 * self.heartbeat_s
                                  if staleness_grace_s is None
                                  else float(staleness_grace_s))
        self.post_timeout_s = float(post_timeout_s)
        self._lock = checked_lock("fleet.member")
        self._peers: List[str] = [p.rstrip("/") for p in peers
                                  if p.rstrip("/") != self.self_url]
        self._seq = 0                      # local bump sequence
        self._hb_seq = 0
        #: (origin, connector_id, table) -> highest folded seq
        self._seen: Dict[Tuple[str, str, str], int] = {}
        #: origin -> {"t": recv monotonic, "groups": {path: counts}}
        self._remote: Dict[str, dict] = {}
        self._lost: set = set()
        self._stopped = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        # the spi listener list is append-only; register a weak
        # trampoline so a dead member (tests build many) costs one
        # dead-ref check per write, never a broadcast
        ref = weakref.ref(self)

        def _listener(conn, table, _ref=ref):
            m = _ref()
            if m is not None:
                m._on_local_write(conn, table)
        spi.on_data_change(_listener)
        if resource_groups is not None:
            # admission federation: can_run_more() consults this member
            resource_groups.federation = self
        if discovery is not None:
            # coordinators are discovery citizens too (role-tagged so
            # they never enter the worker scheduling sweep)
            discovery.announce(node_id, self.self_url, role="coordinator")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Begin the heartbeat loop (idempotent)."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._stopped.clear()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name=f"fleet-hb-{self.node_id}",
            daemon=True)
        self._hb_thread.start()

    def stop(self) -> None:
        """Hard stop (the process-death stand-in in tests): heartbeats
        cease, peers notice via the staleness grace."""
        self._stopped.set()

    def leave(self) -> None:
        """Clean drain: one final ``leaving`` heartbeat so peers drop
        this member's counts NOW (and never count it as lost), then
        stop."""
        try:
            self.heartbeat_once(leaving=True)
        finally:
            self.stop()

    def set_peers(self, peers) -> None:
        with self._lock:
            self._peers = [p.rstrip("/") for p in peers
                           if p.rstrip("/") != self.self_url]

    def peers(self) -> List[str]:
        with self._lock:
            return list(self._peers)

    # -- write bumps: broadcast side -----------------------------------------
    def _catalog_of(self, conn) -> Optional[str]:
        """The cross-process-stable connector identity: its catalog
        name in THIS coordinator's catalog manager. A connector not
        registered here (another embedded server's catalog in the same
        process) is not ours to broadcast."""
        cm = self._catalogs
        if cm is None:
            return None
        try:
            for name in cm.names():
                if cm.get(name) is conn:
                    return name
        except Exception:
            return None
        return None

    _folding = threading.local()

    def _on_local_write(self, conn, table: str) -> None:
        """spi.on_data_change listener: broadcast a LOCAL connector
        write to every peer. Folds of REMOTE bumps re-enter spi inside
        the same thread; the thread-local gate keeps them from
        re-broadcasting (no bump storms, no loops)."""
        if self._stopped.is_set():
            return
        if getattr(FleetMember._folding, "active", False):
            return
        cid = self._catalog_of(conn)
        if cid is None:
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
            peers = list(self._peers)
        try:
            dv = conn.data_version(table)
        except Exception:
            dv = None
        doc = {"origin": self.node_id, "seq": seq,
               "connectorId": cid, "table": table,
               "dataVersion": _jsonable(dv), "writeEpoch": seq}
        for peer in peers:
            try:
                FAILPOINTS.hit("fleet.broadcast",
                               key=f"{cid}/{table}@{peer}")
            except FailpointError:
                # armed drop: the peer never hears about this write —
                # its hit-time data_version revalidation is the net
                _BUMP_DROPPED.inc()
                continue
            try:
                _post_json(f"{peer}/v1/fleet/bump", doc,
                           self.post_timeout_s)
                _BUMP_BROADCAST.inc()
            except Exception:
                # a dead peer must not fail the local write; it will
                # revalidate (and be declared lost on the hb cadence)
                _PEER_POST_FAILURE.inc()

    # -- write bumps: fold side ----------------------------------------------
    def fold_bump(self, doc: dict) -> bool:
        """Fold one remote write bump into the local caches, through
        the SAME audited ``spi.notify_data_change`` path a local write
        takes — every registered cache listener runs its normal
        note_write (epoch bump) + invalidate sequence, so the
        epoch-before-deps veto protects in-flight local inserts against
        this remote write exactly as against a local one.

        Dedupe is per ``(origin, connector, table)`` on the origin's
        monotonic ``seq``; the high-water mark advances only AFTER the
        notify so a fold that dies is retried by the next bump, never
        recorded as delivered."""
        origin = str(doc.get("origin") or "")
        cid = str(doc.get("connectorId") or "")
        table = str(doc.get("table") or "")
        try:
            seq = int(doc.get("seq") or 0)
        except (TypeError, ValueError):
            return False
        if not origin or not cid or not table or origin == self.node_id:
            return False
        key = (origin, cid, table)
        with self._lock:
            if seq <= self._seen.get(key, 0):
                _BUMP_STALE.inc()
                return False
        cm = self._catalogs
        conn = None
        if cm is not None:
            try:
                conn = cm.get(cid)
            except KeyError:
                conn = None
        if conn is None:
            _BUMP_UNKNOWN.inc()
            return False
        FleetMember._folding.active = True
        try:
            spi.notify_data_change(conn, table)
        finally:
            FleetMember._folding.active = False
        with self._lock:
            if seq > self._seen.get(key, 0):
                self._seen[key] = seq
        _BUMP_FOLD.inc()
        return True

    # -- heartbeats ----------------------------------------------------------
    def _serving_counters(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for m in REGISTRY.snapshot():
            if _is_federated_serving(m["name"]):
                out[m["name"]] = m["value"]
        return out

    def heartbeat_once(self, leaving: bool = False) -> None:
        """Push this coordinator's per-group counts (and serving SLO
        counters) to every peer, then sweep for peers gone past the
        staleness grace."""
        groups = {}
        if self._manager is not None:
            groups = self._manager.group_counts()
        with self._lock:
            self._hb_seq += 1
            doc = {"origin": self.node_id, "hbSeq": self._hb_seq,
                   "leaving": bool(leaving), "groups": groups,
                   "serving": self._serving_counters(),
                   # self-identification makes peering dynamic: a
                   # scaled-up coordinator heartbeats its way into
                   # every member's peer list, and a leaving farewell
                   # prunes it back out (autoscaled coordinator tier)
                   "url": self.self_url,
                   "ts": time.time()}
            peers = list(self._peers)
        for peer in peers:
            try:
                _post_json(f"{peer}/v1/fleet/heartbeat", doc,
                           self.post_timeout_s)
                _HEARTBEAT.inc()
            except Exception:
                _PEER_POST_FAILURE.inc()
        self._sweep_lost()

    def _sweep_lost(self) -> None:
        """Declare peers lost (once each) when their last heartbeat
        ages past the grace; their federated counts stop binding
        admission at the same moment (remote_running skips stale
        snapshots), so a queued query blocked on a dead peer's slots
        needs one dispatch kick to proceed."""
        now = time.monotonic()
        kicked = False
        with self._lock:
            for origin, snap in list(self._remote.items()):
                if now - snap["t"] > self.staleness_grace_s \
                        and origin not in self._lost:
                    self._lost.add(origin)
                    # drop the corpse's per-group counts: the
                    # survivors absorb its admission share at the
                    # same instant the loss is declared
                    self._remote.pop(origin, None)
                    _COORDINATOR_LOST.inc()
                    kicked = True
        if kicked and self._manager is not None:
            self._manager._dispatch()

    def fold_heartbeat(self, doc: dict) -> bool:
        origin = str(doc.get("origin") or "")
        if not origin or origin == self.node_id:
            return False
        leaving = bool(doc.get("leaving"))
        url = str(doc.get("url") or "").rstrip("/")
        with self._lock:
            if leaving:
                # clean drain: counts drop immediately, and the member
                # is forgotten — NOT a loss. The EXPLICIT deregister
                # (vs waiting out the staleness grace): its url leaves
                # the peer list now, so no further broadcast/heartbeat
                # is ever addressed to the drained coordinator
                self._remote.pop(origin, None)
                self._lost.discard(origin)
                if url and url in self._peers:
                    self._peers.remove(url)
            else:
                self._remote[origin] = {
                    "t": time.monotonic(),
                    "groups": dict(doc.get("groups") or {})}
                self._lost.discard(origin)
                # dynamic peering: an autoscaled-up coordinator only
                # knows the incumbents — its first heartbeat teaches
                # each of them its url (docs without "url" — older
                # members, hand-built tests — change nothing)
                if url and url != self.self_url \
                        and url not in self._peers:
                    self._peers.append(url)
        if leaving and self._discovery is not None:
            # drop its coordinator record from the shared membership
            # immediately too (role="coordinator" entries never enter
            # worker scheduling, but status surfaces read them)
            self._discovery.remove(origin)
        _HEARTBEAT_FOLD.inc()
        # federate the peer's serving counters into the local store
        # (the PR 16 record() path, origin-tagged like worker series):
        # any coordinator's SLO plane can aggregate fleet-wide traffic
        from ..obs.timeseries import TIMESERIES
        for name, v in (doc.get("serving") or {}).items():
            if isinstance(name, str) and _is_federated_serving(name):
                try:
                    TIMESERIES.record(f"{name}.{origin}", float(v),
                                      kind="counter")
                except (TypeError, ValueError):
                    pass
        # remote counts may have DECREASED — wake queued admissions
        if self._manager is not None:
            self._manager._dispatch()
        return True

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            try:
                self.heartbeat_once()
            except Exception:
                pass

    # -- federation provider (resource_groups.can_run_more) ------------------
    def remote_running(self, path: str) -> int:
        """Sum of fresh remote running counts for one group path.
        Called under manager.lock — takes only the fleet lock (lock
        order manager.lock -> fleet.member; the fold side never calls
        back into the manager while holding the fleet lock)."""
        now = time.monotonic()
        total = 0
        with self._lock:
            for snap in self._remote.values():
                if now - snap["t"] > self.staleness_grace_s:
                    continue
                g = snap["groups"].get(path)
                if g:
                    total += int(g.get("running", 0) or 0)
        return total

    def remote_memory(self, path: str) -> int:
        now = time.monotonic()
        total = 0
        with self._lock:
            for snap in self._remote.values():
                if now - snap["t"] > self.staleness_grace_s:
                    continue
                g = snap["groups"].get(path)
                if g:
                    total += int(g.get("memory", 0) or 0)
        return total

    def note_remote_blocked(self) -> None:
        """Admission accounting hook: a query a coordinator-local view
        would have admitted was blocked by federated counts."""
        _REMOTE_BLOCKED.inc()

    # -- introspection -------------------------------------------------------
    def status(self) -> dict:
        now = time.monotonic()
        workers: List[str] = []
        if self._discovery is not None:
            try:
                workers = self._discovery.active_urls()
            except Exception:
                workers = []
        with self._lock:
            return {
                "nodeId": self.node_id,
                "url": self.self_url,
                "peers": list(self._peers),
                "workers": sorted(workers),
                "seq": self._seq,
                "heartbeatS": self.heartbeat_s,
                "stalenessGraceS": self.staleness_grace_s,
                "remote": {o: {"age_s": round(now - s["t"], 3),
                               "groups": s["groups"]}
                           for o, s in sorted(self._remote.items())},
                "lost": sorted(self._lost),
            }

"""Compiled-plan cache: repeated statements skip parse/plan/optimize.

tf.data (PAPERS.md) found that steady-state input-pipeline cost is
dominated by REUSE of a compiled pipeline, not its construction; the
serving analogue here is a dashboard firing the same parameterized
statement hundreds of times. The reference re-plans every EXECUTE
(presto-main/.../execution/SqlQueryExecution.java builds a fresh plan
per query); this engine's jit cache (``ops/jitcache``) already dedupes
*executables* — this module lifts the same idea to whole optimized
plans, following the scancache invalidation idioms:

- **Key** — sha256 fingerprint of the canonical bound AST (frozen
  dataclasses, so ``repr`` is canonical), the session's catalog/schema,
  the full session-property overlay, the view definitions, and — when
  access control is active — the user. EXECUTE substitutes parameters
  before planning, so two EXECUTEs of one prepared statement with the
  same arguments share an entry.
- **Validation** — each entry records the connector ``data_version``
  of every table its plan scans (captured at plan time). A hit
  re-checks versions under the lock; any drift drops the entry
  (``plan_cache_invalidated_total``) and replans — the same
  write-invalidation contract the scan cache keeps. Connector writes
  additionally invalidate eagerly through ``spi.on_data_change``.
  Plans over versionless connectors (``data_version`` → None, e.g.
  live system tables) are never cached.
- **Safety** — plan nodes are frozen dataclasses and all executor
  state (dynamic filters, materialization, lifespans, stats) lives in
  the per-query ``_Executor``, so one plan object can be executed by
  any number of concurrent queries.

Session knob: ``plan_cache`` (default true). The capacity is
process-wide (plans are small ASTs; 256 entries), like the jit cache.

Metrics: ``plan_cache_{hit,miss,invalidated,evicted}_total`` — on
``system.runtime.metrics`` and ``/v1/metrics``.
"""
from __future__ import annotations

import hashlib
import weakref
from collections import OrderedDict
from typing import List, Optional, Tuple

from .._devtools.lockcheck import checked_lock, guarded_by
from ..exec.failpoints import FAILPOINTS
from ..obs.metrics import REGISTRY

DEFAULT_CAPACITY = 256


def _freeze(v):
    """Hashable/comparable form of a connector data-version payload
    (mirrors exec/scancache._freeze — versions are opaque and may carry
    lists/dicts)."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


class IdentMemo:
    """Identity-keyed LRU for artifacts derived from interned objects
    (parse_cached returns the SAME AST per repeated text). Entries PIN
    their key object, so an id() can never be reused while its entry
    lives; bounded like the statement cache itself. Shared by the
    canonical-repr memo here and the template parameterization memo
    (serving/template.py) — one implementation owns the id-reuse pin
    and cap policy."""

    #: guarded-field contract (lockcheck): the memo map may only be
    #: touched under this instance's lock
    _entries = guarded_by(attr="_lock")

    def __init__(self, cap: int = 512, lock_name: str = "plancache.memo"):
        self._cap = cap
        self._entries: "OrderedDict[int, Tuple]" = OrderedDict()
        self._lock = checked_lock(lock_name)

    def get(self, obj, compute):
        key = id(obj)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0] is obj:
                self._entries.move_to_end(key)
                return hit[1]
        value = compute(obj)
        with self._lock:
            self._entries[key] = (obj, value)
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)
        return value


class _Entry:
    __slots__ = ("plan", "deps")

    def __init__(self, plan, deps):
        self.plan = plan
        #: [(connector weakref, catalog, table, frozen data version)]
        self.deps: List[Tuple] = deps


class PlanCache:
    """Process-wide LRU of optimized logical plans (the whole-plan
    sibling of the jit executable cache). ``metrics`` names the counter
    family (the template cache instantiates a second PlanCache under
    ``plan_template_cache``); ``get`` returns what ``put`` stored — by
    default the plan itself, or an arbitrary payload (template entries
    carry plan + guards) whose deps still come from the plan."""

    #: guarded-field contracts (lockcheck): the entry map and the write
    #: epoch may only be touched under this instance's lock — the
    #: attr= form resolves the required lock NAME per instance, since
    #: the template cache instantiates this class under its own name
    _entries = guarded_by(attr="_lock")
    _epoch = guarded_by(attr="_lock")

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 metrics: str = "plan_cache",
                 lock_name: str = "plancache.entries"):
        self.capacity = capacity
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        #: bumped on every connector write notification; plans begun
        #: before a write may not insert after it (see put())
        self._epoch = 0
        self._lock = checked_lock(lock_name)
        self._hits = REGISTRY.counter(f"{metrics}_hit_total")
        self._misses = REGISTRY.counter(f"{metrics}_miss_total")
        self._invalidated = REGISTRY.counter(f"{metrics}_invalidated_total")
        self._evicted = REGISTRY.counter(f"{metrics}_evicted_total")

    # -- keying ---------------------------------------------------------------
    #: statement-repr memo: a serving query fingerprints twice
    #: (template + bound key) — the O(tree) repr is paid once
    _repr_memo = IdentMemo(lock_name="plancache.reprs")

    @classmethod
    def _stmt_repr(cls, stmt) -> bytes:
        return cls._repr_memo.get(stmt, lambda s: repr(s).encode())

    @classmethod
    def session_fragment(cls, session, user: str = "") -> bytes:
        """Everything :meth:`fingerprint` hashes beyond the statement
        repr. Exposed so a caller keying SEVERAL statements against one
        (session, user) — a serving query fingerprints both its bound
        form and its parameterized template — pays the session-slice
        walk once and hands the bytes to each call."""
        cats = getattr(session.catalogs, "_inner", session.catalogs)
        # connector identities: two runners mounting same-named catalogs
        # over DIFFERENT connector instances (separate datasets) must
        # not share fingerprints — plans embed stats/bounds captured
        # from one instance's data. id() reuse after GC is covered by
        # the entry's weakref deps check (a dead dep drops the entry).
        try:
            ids = sorted((n, id(cats.get(n))) for n in cats.names())
        except Exception:
            ids = [("<unresolvable>", 0)]
        return b"".join((
            repr((session.catalog, session.schema)).encode(),
            repr(ids).encode(),
            repr(sorted((k, repr(v)) for k, v in
                        session.properties.items())).encode(),
            repr(sorted((k, repr(v)) for k, v in
                        session.views.items())).encode(),
            user.encode(),
        ))

    @classmethod
    def fingerprint(cls, stmt, session, user: str = "",
                    fragment: Optional[bytes] = None) -> bytes:
        """Canonical statement fingerprint. The AST and its literals are
        frozen dataclasses, so ``repr`` is a stable canonical form; the
        session slice covers everything that can change what ``optimize``
        produces (properties drive optimizer gates, views expand at plan
        time, the user scopes secured-catalog resolution). ``fragment``
        must be this (session, user)'s :meth:`session_fragment` when
        supplied."""
        h = hashlib.sha256()
        h.update(cls._stmt_repr(stmt))
        h.update(fragment if fragment is not None
                 else cls.session_fragment(session, user))
        return h.digest()

    @staticmethod
    def _plan_deps(plan, session) -> Optional[List[Tuple]]:
        """Data-version deps of every table the plan scans, or None when
        any scanned connector cannot attest a version (uncacheable)."""
        from ..planner.plan import TableScanNode
        deps: List[Tuple] = []
        seen = set()

        def walk(node):
            if isinstance(node, TableScanNode):
                key = (node.catalog, node.table.table)
                if key not in seen:
                    seen.add(key)
                    conn = session.catalogs.get(node.catalog)
                    ver_fn = getattr(conn, "data_version", None)
                    version = ver_fn(node.table.table) if ver_fn else None
                    if version is None:
                        return False
                    deps.append((weakref.ref(conn), node.catalog,
                                 node.table.table, _freeze(version)))
            return all(walk(c) for c in node.children)

        for root in [plan.root] + list(plan.init_plans):
            if not walk(root):
                return None
        return deps

    @staticmethod
    def _dep_live(dep) -> bool:
        conn_ref, _catalog, table, version = dep
        conn = conn_ref()
        if conn is None:
            return False
        ver_fn = getattr(conn, "data_version", None)
        if ver_fn is None:
            return False
        return _freeze(ver_fn(table)) == version

    # -- lookup / insert ------------------------------------------------------
    def epoch(self) -> int:
        """Current write epoch — capture BEFORE planning and hand to
        :meth:`put` so a write landing mid-plan can veto the insert."""
        with self._lock:
            return self._epoch

    def note_write(self) -> None:
        with self._lock:
            self._epoch += 1

    def get(self, key: bytes):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._misses.inc()
                return None
            deps = list(e.deps)
        # revalidate OUTSIDE the lock: data_version may touch the
        # filesystem (filebase stats every table file) and must not
        # serialize every concurrent warm query behind one connector's
        # I/O on the latency-critical fast path
        if not all(self._dep_live(d) for d in deps):
            # a write landed since this plan was optimized: its
            # attached stats/bounds may be stale — replan
            with self._lock:
                if self._entries.get(key) is e:
                    del self._entries[key]
                    self._invalidated.inc()
            self._misses.inc()
            return None
        with self._lock:
            if self._entries.get(key) is e:
                self._entries.move_to_end(key)
        self._hits.inc()
        return e.plan

    def put(self, key: bytes, plan, session,
            epoch: Optional[int] = None, payload=None) -> bool:
        """Insert a freshly-optimized plan. ``epoch`` is the write epoch
        captured BEFORE planning began: any connector write notifying
        during the plan/optimize window bumps the epoch and vetoes the
        insert — the version stamps read here (post-plan) would
        otherwise validate a plan whose optimizer-time stats predate
        the write (TOCTOU). External mutations that bypass
        notify_data_change are caught by get()'s per-hit revalidation
        instead (data_version fingerprints file mtimes). ``payload``
        (default: the plan) is what a later get() returns."""
        deps = self._plan_deps(plan, session)
        if deps is None:
            return False
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                return False
            if key in self._entries:
                return True            # first planner won; identical plan
            self._entries[key] = _Entry(
                payload if payload is not None else plan, deps)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._evicted.inc()
            return True

    # -- invalidation ---------------------------------------------------------
    def invalidate(self, conn=None, table: Optional[str] = None) -> None:
        """Drop entries depending on a connector (and optionally one
        table) — the eager half of write invalidation, riding the same
        ``spi.notify_data_change`` path as the scan cache."""
        with self._lock:
            victims = []
            for key, e in self._entries.items():
                for conn_ref, _cat, tab, _ver in e.deps:
                    ref = conn_ref()
                    if ref is None:
                        victims.append(key)
                        break
                    if conn is not None and ref is not conn:
                        continue
                    if table is not None and tab != table:
                        continue
                    victims.append(key)
                    break
            for key in victims:
                del self._entries[key]
            if victims:
                self._invalidated.inc(len(victims))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: the process-wide cache (plans are connector-bound via their deps, so
#: one cache serves every runner in the process, like exec/scancache)
PLANS = PlanCache()

from ..connectors import spi  # noqa: E402


def _on_write(conn, table) -> None:
    PLANS.note_write()
    PLANS.invalidate(conn, table)


spi.on_data_change(_on_write)


# -- statement (parse) cache -------------------------------------------------
# The front half of the repeated-statement fast path: identical SQL text
# reuses the parsed AST (frozen dataclasses — reusable across queries),
# so a warm statement pays neither parse nor plan. Small and capped: SQL
# text keys can be long, but serving traffic repeats a handful of shapes.

_STMT_CAP = 512
_STMT_MAX_LEN = 1 << 16
_stmt_entries: "OrderedDict[str, object]" = OrderedDict()
_stmt_lock = checked_lock("plancache.statements")


def parse_cached(sql: str):
    """``sql.parser.parse_statement`` with text-keyed memoization."""
    from ..sql.parser import parse_statement
    if len(sql) > _STMT_MAX_LEN:
        return parse_statement(sql)
    with _stmt_lock:
        stmt = _stmt_entries.get(sql)
        if stmt is not None:
            _stmt_entries.move_to_end(sql)
            return stmt
    stmt = parse_statement(sql)
    with _stmt_lock:
        _stmt_entries[sql] = stmt
        while len(_stmt_entries) > _STMT_CAP:
            _stmt_entries.popitem(last=False)
    return stmt


def key_fragment(session, user: str = "",
                 secured: bool = False) -> bytes:
    """The (session, user) fragment under the same key rule as
    :func:`bound_fingerprint` — compute once, pass to several
    ``bound_fingerprint`` calls keying against the same session."""
    return PlanCache.session_fragment(session,
                                      user=user if secured else "")


def bound_fingerprint(stmt, session, user: str = "",
                      secured: bool = False,
                      fragment: Optional[bytes] = None) -> bytes:
    """THE bound-statement key rule (user folds in only when access
    control is active) — every consumer (plan cache, template cache's
    fallback key, result cache, EXPLAIN ANALYZE's probe) must go
    through here so they can never diverge on what a key covers.
    ``fragment``, when supplied, must come from :func:`key_fragment`
    with the same (session, user, secured)."""
    return PlanCache.fingerprint(stmt, session,
                                 user=user if secured else "",
                                 fragment=fragment)


def cached_plan(stmt, session, user: str = "", secured: bool = False):
    """Optimized plan for a SELECT statement, served from :data:`PLANS`
    when the ``plan_cache`` session property (default true) allows and
    the statement's tables are version-attested. ``secured`` folds the
    user into the key so access-control outcomes can never be shared
    across principals."""
    from ..planner.optimizer import optimize
    from ..planner.planner import bool_property, plan_query
    if not bool_property(session, "plan_cache", True):
        return optimize(plan_query(stmt, session), session)
    key = PlanCache.fingerprint(stmt, session,
                                user=user if secured else "")
    plan = PLANS.get(key)
    if plan is not None:
        return plan
    epoch = PLANS.epoch()      # before planning: a mid-plan write vetoes
    # the PR 8 TOCTOU window: the interleaving explorer deschedules
    # here (tests/test_interleave.py) to land a connector write
    # mid-plan and assert the epoch veto holds
    FAILPOINTS.hit("plancache.plan", key=key.hex()[:12])
    plan = optimize(plan_query(stmt, session), session)
    PLANS.put(key, plan, session, epoch=epoch)
    return plan

"""File-format readers (reference presto-orc/, presto-parquet/)."""

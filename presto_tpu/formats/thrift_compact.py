"""Thrift compact-protocol reader/writer (Parquet metadata encoding).

Hand-written minimal codec — the no-codegen analogue of the reference's
parquet-format thrift bindings (reference presto-parquet depends on the
generated org.apache.parquet.format structs; this build parses the same
wire format directly). Values decode into {field_id: value} dicts; struct
shape knowledge lives in the callers (parquet.py's dataclass builders).

Compact protocol essentials: per-field header byte (delta<<4 | type) with
zigzag-varint escape for long deltas; zigzag varints for integers; varint
length-prefixed binary; list header (size<<4 | elem_type) with size=15
escape; BOOL encodes its value in the field type nibble.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

STOP = 0
BOOL_TRUE = 1
BOOL_FALSE = 2
BYTE = 3
I16 = 4
I32 = 5
I64 = 6
DOUBLE = 7
BINARY = 8
LIST = 9
SET = 10
MAP = 11
STRUCT = 12


def _varint(data: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def read_struct(data: bytes, pos: int = 0) -> Tuple[Dict[int, Any], int]:
    """Parse one struct into {field_id: python value}."""
    out: Dict[int, Any] = {}
    field_id = 0
    while True:
        header = data[pos]
        pos += 1
        if header == STOP:
            return out, pos
        delta = header >> 4
        ftype = header & 0x0F
        if delta:
            field_id += delta
        else:
            raw, pos = _varint(data, pos)
            field_id = _zigzag(raw)
        value, pos = _read_value(data, pos, ftype)
        out[field_id] = value


def _read_value(data: bytes, pos: int, ftype: int) -> Tuple[Any, int]:
    if ftype == BOOL_TRUE:
        return True, pos
    if ftype == BOOL_FALSE:
        return False, pos
    if ftype == BYTE:
        return int.from_bytes(data[pos:pos + 1], "little", signed=True), pos + 1
    if ftype in (I16, I32, I64):
        raw, pos = _varint(data, pos)
        return _zigzag(raw), pos
    if ftype == DOUBLE:
        import struct
        return struct.unpack("<d", data[pos:pos + 8])[0], pos + 8
    if ftype == BINARY:
        ln, pos = _varint(data, pos)
        return bytes(data[pos:pos + ln]), pos + ln
    if ftype in (LIST, SET):
        header = data[pos]
        pos += 1
        size = header >> 4
        etype = header & 0x0F
        if size == 15:
            size, pos = _varint(data, pos)
        items: List[Any] = []
        for _ in range(size):
            v, pos = _read_value(data, pos, etype)
            items.append(v)
        return items, pos
    if ftype == STRUCT:
        return read_struct(data, pos)
    raise ValueError(f"unsupported thrift compact type {ftype}")


# ---------------------------------------------------------------------------
# Writer (for the test-fixture Parquet writer)
# ---------------------------------------------------------------------------

def _w_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _w_zigzag(v: int) -> bytes:
    return _w_varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)


def write_struct(fields: List[Tuple[int, int, Any]]) -> bytes:
    """fields = [(field_id, type, value)] in ascending id order."""
    out = bytearray()
    last = 0
    for fid, ftype, value in fields:
        if value is None:
            continue
        wire_type = ftype
        if ftype == BOOL_TRUE:           # caller passes BOOL_TRUE for bools
            wire_type = BOOL_TRUE if value else BOOL_FALSE
        delta = fid - last
        if 0 < delta <= 15:
            out.append((delta << 4) | wire_type)
        else:
            out.append(wire_type)
            out += _w_zigzag(fid)
        last = fid
        out += _write_value(wire_type, value)
    out.append(STOP)
    return bytes(out)


def _write_value(ftype: int, value: Any) -> bytes:
    if ftype in (BOOL_TRUE, BOOL_FALSE):
        return b""
    if ftype in (I16, I32, I64):
        return _w_zigzag(int(value))
    if ftype == DOUBLE:
        import struct
        return struct.pack("<d", value)
    if ftype == BINARY:
        if isinstance(value, str):
            value = value.encode()
        return _w_varint(len(value)) + value
    if ftype == LIST:
        etype, items = value            # caller passes (elem_type, [encoded])
        size = len(items)
        out = bytearray()
        if size < 15:
            out.append((size << 4) | etype)
        else:
            out.append(0xF0 | etype)
            out += _w_varint(size)
        for it in items:
            if etype in (I16, I32, I64):
                out += _w_zigzag(int(it))
            elif etype == BINARY:
                b = it.encode() if isinstance(it, str) else it
                out += _w_varint(len(b)) + b
            elif etype == STRUCT:
                out += it               # pre-encoded struct bytes
            else:
                raise ValueError(f"list elem type {etype}")
        return bytes(out)
    if ftype == STRUCT:
        return value                    # pre-encoded struct bytes
    raise ValueError(f"unsupported write type {ftype}")

"""Minimal protobuf wire-format reader for ORC metadata.

ORC stores its postscript/footer/stripe-footer metadata as protocol
buffers (reference presto-orc/.../metadata/OrcMetadataReader.java parses
the same messages via protobuf-generated classes). The ORC proto schema
is small and frozen, so a hand-rolled wire reader (varints + length-
delimited fields) avoids a protoc dependency.
"""
from __future__ import annotations

from typing import Dict, List, Tuple, Union

Value = Union[int, bytes, List]


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse_message(buf: bytes) -> Dict[int, List[Value]]:
    """Parse one protobuf message into {field_number: [values...]}.

    Wire types handled: 0 = varint, 1 = fixed64, 2 = length-delimited,
    5 = fixed32. Nested messages stay as bytes for the caller to parse.
    """
    fields: Dict[int, List[Value]] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = read_varint(buf, pos)
        elif wire == 1:
            v = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wire == 2:
            ln, pos = read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def first(fields: Dict[int, List[Value]], num: int, default=None):
    vals = fields.get(num)
    return vals[0] if vals else default


def packed_varints(data: bytes) -> List[int]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = read_varint(data, pos)
        out.append(v)
    return out

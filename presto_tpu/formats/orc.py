"""ORC file reader: host metadata/pruning, device column decode.

Port of concept from the reference's from-scratch ORC reader (reference
presto-orc/.../OrcReader.java:50 parses the tail;
OrcRecordReader.java:70,366 iterates stripes and materializes columns
via per-type stream readers; TupleDomainOrcPredicate.java:77 prunes
stripes on min/max statistics). TPU-first split: stripe/footer parsing
and pruning stay on host; the bulk value decode (RLEv2 bit-unpacking,
IEEE byte assembly) runs as vectorized device kernels (orc_rle.py), and
columns land directly as device-resident ``Column``s.

IO is ranged: the tail parses from a bounded suffix read and each stripe
reads exactly its byte range — no whole-file slurp.

Supported today: struct root over boolean/byte/int/long/short/float/
double/string/varchar/char/date columns, NONE or ZLIB compression,
DIRECT/DIRECT_V2/DICTIONARY_V2 encodings, nulls via present streams,
file- and stripe-level min/max pruning.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity
from .orc_meta import (
    ColumnIntStats, OrcFileTail, StripeFooter, StripeInfo,
    decompress_stream, parse_stripe_footer, read_tail, tail_size_needed,
)
from .orc_rle import (
    decode_byte_rle, decode_present, decode_rle_v2_device,
    decode_rle_v2_numpy,
)

_ORC_TO_ENGINE = {
    "boolean": T.BOOLEAN,
    "byte": T.TINYINT,
    "short": T.SMALLINT,
    "int": T.INTEGER,
    "long": T.BIGINT,
    "float": T.DOUBLE,
    "double": T.DOUBLE,
    "string": T.VARCHAR,
    "varchar": T.VARCHAR,
    "char": T.VARCHAR,
    "date": T.DATE,
}

_TAIL_GUESS = 64 * 1024


@dataclasses.dataclass
class OrcColumn:
    name: str
    orc_id: int            # type id in the ORC schema tree
    orc_kind: str
    type: T.Type


class OrcReader:
    """One ORC file; column-projected, stripe-granular batch iterator."""

    def __init__(self, path: str):
        self.path = path
        self._size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, self._size - _TAIL_GUESS))
            suffix = f.read()
            needed = tail_size_needed(suffix)
            if needed > len(suffix):
                f.seek(self._size - needed)
                suffix = f.read()
        self.tail: OrcFileTail = read_tail(suffix)
        root = self.tail.types[0]
        if root.kind != "struct":
            raise ValueError("only struct-rooted ORC files are supported")
        self.columns: List[OrcColumn] = []
        for name, tid in zip(root.field_names, root.subtypes):
            t = self.tail.types[tid]
            if t.kind == "decimal":
                if (t.precision or 38) > 18:
                    # engine decimals are i64-backed (<= 18 digits) until
                    # the 2xi64 int128 path lands
                    raise NotImplementedError(
                        f"ORC decimal({t.precision},{t.scale}) exceeds "
                        "the supported precision 18")
                engine_t = T.DecimalType(t.precision or 38, t.scale or 0)
            elif t.kind not in _ORC_TO_ENGINE:
                raise NotImplementedError(
                    f"ORC column type {t.kind!r} is not supported")
            else:
                engine_t = _ORC_TO_ENGINE[t.kind]
            if t.kind in ("varchar", "char") and t.max_length:
                engine_t = T.varchar(t.max_length)
            self.columns.append(OrcColumn(name, tid, t.kind, engine_t))

    def _read_range(self, offset: int, length: int) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    @property
    def schema(self) -> Schema:
        return Schema([(c.name, c.type) for c in self.columns])

    @property
    def num_rows(self) -> int:
        return self.tail.num_rows

    # -- pruning -------------------------------------------------------------
    def _excluded(self, stats: Dict[int, ColumnIntStats],
                  min_max: Dict[str, Tuple[int, int]]) -> bool:
        by_name = {c.name: c for c in self.columns}
        for name, (lo, hi) in min_max.items():
            c = by_name.get(name)
            if c is None:
                continue
            st = stats.get(c.orc_id)
            if st is None or st.min is None or st.max is None:
                continue
            # lo/hi of None = unbounded on that side
            if ((lo is not None and st.max < lo)
                    or (hi is not None and st.min > hi)):
                return True
        return False

    def file_prunable(self, min_max: Dict[str, Tuple[int, int]]) -> bool:
        return bool(min_max) and self._excluded(self.tail.int_stats,
                                                min_max)

    def stripe_prunable(self, stripe_index: int,
                        min_max: Dict[str, Tuple[int, int]]) -> bool:
        """Per-stripe min/max exclusion from the metadata section
        (reference TupleDomainOrcPredicate.java:77 over
        StripeStatistics)."""
        if not min_max or stripe_index >= len(self.tail.stripe_stats):
            return False
        return self._excluded(self.tail.stripe_stats[stripe_index],
                              min_max)

    # -- stripe decode -------------------------------------------------------
    def read_stripe(self, stripe: StripeInfo,
                    names: Sequence[str]) -> Batch:
        body = self._read_range(
            stripe.offset,
            stripe.index_length + stripe.data_length
            + stripe.footer_length)
        footer = parse_stripe_footer(
            body[stripe.index_length + stripe.data_length:],
            self.tail.compression)
        n = stripe.num_rows
        cap = bucket_capacity(n)
        by_name = {c.name: c for c in self.columns}
        cols: List[Column] = []
        fields: List[Tuple[str, T.Type]] = []
        for name in names:
            c = by_name[name]
            cols.append(self._decode_column(c, footer, body, n, cap))
            fields.append((name, c.type))
        mask = jnp.arange(cap) < n
        return Batch(Schema(fields), cols, mask)

    def batches(self, names: Optional[Sequence[str]] = None,
                min_max: Optional[Dict[str, Tuple[int, int]]] = None
                ) -> Iterator[Batch]:
        names = list(names) if names is not None \
            else [c.name for c in self.columns]
        if min_max and self.file_prunable(min_max):
            return
        for si, stripe in enumerate(self.tail.stripes):
            if min_max and self.stripe_prunable(si, min_max):
                continue
            yield self.read_stripe(stripe, names)

    # -- column decoders -----------------------------------------------------
    def _streams(self, footer: StripeFooter, body: bytes,
                 orc_id: int) -> Dict[str, bytes]:
        out = {}
        for s in footer.streams:
            if s.column == orc_id and s.kind in (
                    "present", "data", "length", "dictionary_data",
                    "secondary"):
                raw = body[s.offset:s.offset + s.length]
                out[s.kind] = decompress_stream(raw,
                                                self.tail.compression)
        return out

    def _decode_column(self, c: OrcColumn, footer: StripeFooter,
                       body: bytes, n: int, cap: int) -> Column:
        enc = footer.encodings[c.orc_id]
        streams = self._streams(footer, body, c.orc_id)
        present = streams.get("present")
        if present is not None:
            validity_np = decode_present(present, n)
        else:
            validity_np = np.ones(n, dtype=bool)
        n_values = int(validity_np.sum())
        validity = np.zeros(cap, dtype=bool)
        validity[:n] = validity_np

        def scatter_i64(vals: jnp.ndarray) -> jnp.ndarray:
            """Spread n_values decoded values to their row slots."""
            if n_values == n:
                return vals[:cap] if vals.shape[0] >= cap else jnp.pad(
                    vals, (0, cap - vals.shape[0]))
            pos = np.zeros(cap, dtype=np.int64)
            pos[np.nonzero(validity)[0]] = np.arange(n_values)
            return jnp.take(vals, jnp.asarray(pos), axis=0)

        data = streams.get("data", b"")
        if c.orc_kind in ("long", "int", "short", "date"):
            vals = decode_rle_v2_device(data, n_values, signed=True,
                                        capacity=bucket_capacity(
                                            max(n_values, 1)))
            out = scatter_i64(vals)
            dt = c.type.storage_dtype
            return Column(c.type, out.astype(dt), jnp.asarray(validity),
                          None)
        if c.orc_kind == "byte":
            # sign-extend: ORC byte is a signed tinyint
            vals = decode_byte_rle(data, n_values).view(np.int8) \
                .astype(np.int64)
            out = scatter_i64(jnp.asarray(vals))
            return Column(c.type, out.astype(c.type.storage_dtype),
                          jnp.asarray(validity), None)
        if c.orc_kind == "boolean":
            bits = decode_present(data, n_values)
            out = scatter_i64(jnp.asarray(bits.astype(np.int64)))
            return Column(c.type, out.astype(bool),
                          jnp.asarray(validity), None)
        if c.orc_kind in ("double", "float"):
            width = 8 if c.orc_kind == "double" else 4
            raw = np.frombuffer(data, dtype=np.uint8)[:n_values * width]
            u8 = jnp.asarray(raw)
            vals = _assemble_ieee(u8, n_values, width)
            out = scatter_i64(vals)
            return Column(c.type, out.astype(jnp.float64),
                          jnp.asarray(validity), None)
        if c.orc_kind == "decimal":
            # DATA = zigzag base-128 varint unscaled values, SECONDARY =
            # per-value scale (reference stream/DecimalInputStream.java);
            # rescale to the declared scale and store i64
            from .orc_rle import decode_rle_v2_numpy
            scales = decode_rle_v2_numpy(
                streams.get("secondary", b""), n_values, signed=True)
            target = c.type.scale
            mant = np.empty(n_values, dtype=np.int64)
            pos = 0
            for i in range(n_values):
                result = 0
                shift = 0
                while True:
                    b = data[pos]
                    pos += 1
                    result |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                # exact python-int arithmetic, bounds-checked into i64
                v = (result >> 1) ^ -(result & 1)
                d = target - int(scales[i])
                if d > 0:
                    v *= 10 ** d
                elif d < 0:
                    v //= 10 ** (-d)
                if not -(2 ** 63) <= v < 2 ** 63:
                    raise OverflowError(
                        f"decimal value out of i64 range in {c.name!r}")
                mant[i] = v
            out = scatter_i64(jnp.asarray(mant))
            return Column(c.type, out.astype(c.type.storage_dtype),
                          jnp.asarray(validity), None)
        if c.orc_kind in ("string", "varchar", "char"):
            return self._decode_string(c, enc, footer, streams, cap,
                                       validity, n_values, scatter_i64)
        raise NotImplementedError(c.orc_kind)

    def _decode_string(self, c, enc, footer: StripeFooter, streams, cap,
                       validity, n_values, scatter_i64) -> Column:
        if enc == "dictionary_v2":
            dict_size = footer.dictionary_sizes[c.orc_id]
            lengths = decode_rle_v2_numpy(
                streams.get("length", b""), dict_size, signed=False)
            blob = streams.get("dictionary_data", b"")
            vocab: List[str] = []
            pos = 0
            for ln in lengths:
                vocab.append(blob[pos:pos + int(ln)].decode(
                    "utf-8", "replace"))
                pos += int(ln)
            codes = decode_rle_v2_device(
                streams.get("data", b""), n_values, signed=False,
                capacity=bucket_capacity(max(n_values, 1)))
            out = scatter_i64(codes)
            return Column(c.type, out.astype(jnp.int32),
                          jnp.asarray(validity),
                          tuple(vocab) or ("",))
        if enc == "direct_v2":
            lengths = decode_rle_v2_numpy(
                streams.get("length", b""), n_values, signed=False)
            blob = streams.get("data", b"")
            values: List[str] = []
            pos = 0
            for ln in lengths:
                values.append(blob[pos:pos + int(ln)].decode(
                    "utf-8", "replace"))
                pos += int(ln)
            vocab_list = sorted(set(values))
            lookup = {s: i for i, s in enumerate(vocab_list)}
            codes_np = np.asarray([lookup[s] for s in values],
                                  dtype=np.int64)
            out = scatter_i64(jnp.asarray(codes_np))
            return Column(c.type, out.astype(jnp.int32),
                          jnp.asarray(validity),
                          tuple(vocab_list) or ("",))
        raise NotImplementedError(f"string encoding {enc!r}")


@jax.jit
def _assemble_ieee_f64(u8: jnp.ndarray) -> jnp.ndarray:
    b = u8.reshape(-1, 8).astype(jnp.uint64)
    shifts = (jnp.uint64(8) * jnp.arange(8, dtype=jnp.uint64))[None, :]
    word = jnp.sum(b << shifts, axis=1)
    return jax.lax.bitcast_convert_type(word, jnp.float64)


@jax.jit
def _assemble_ieee_f32(u8: jnp.ndarray) -> jnp.ndarray:
    b = u8.reshape(-1, 4).astype(jnp.uint32)
    shifts = (jnp.uint32(8) * jnp.arange(4, dtype=jnp.uint32))[None, :]
    word = jnp.sum(b << shifts, axis=1)
    return jax.lax.bitcast_convert_type(word, jnp.float32)


def _assemble_ieee(u8: jnp.ndarray, n_values: int, width: int):
    if width == 8:
        return _assemble_ieee_f64(u8[:n_values * 8])
    return _assemble_ieee_f32(u8[:n_values * 4]).astype(jnp.float64)

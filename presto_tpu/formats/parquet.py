"""Parquet file reader: host metadata/pruning, device column decode.

Port of concept from the reference's from-scratch Parquet reader
(reference presto-parquet/.../reader/ParquetReader.java + per-type
PrimitiveColumnReader, RunLengthBitPackingHybridDecoder,
predicate/TupleDomainParquetPredicate.java row-group pruning). TPU-first
split, mirroring formats/orc.py: footer/page-header parsing and
row-group pruning stay on host; the bulk decode of the RLE/bit-packed
hybrid (dictionary indices, definition levels, booleans) runs as one
vectorized device kernel over the raw page bytes, and dictionary-encoded
string columns land directly as engine dictionary codes — Parquet's
dictionary IS the engine's vocab, no re-encoding.

Supported: flat schemas over BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY
(+DATE/TIMESTAMP/DECIMAL/UTF8 logical types), V1 data pages,
PLAIN + PLAIN_DICTIONARY/RLE_DICTIONARY encodings, UNCOMPRESSED or GZIP
codecs, nulls via definition levels, row-group min/max pruning.
"""
from __future__ import annotations

import dataclasses
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.prefix import prefix_sum
from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity
from . import thrift_compact as tc

MAGIC = b"PAR1"

# physical types (parquet.thrift Type)
P_BOOLEAN, P_INT32, P_INT64, P_INT96, P_FLOAT, P_DOUBLE, P_BYTE_ARRAY, \
    P_FIXED = range(8)
# encodings
E_PLAIN, _, E_PLAIN_DICT, E_RLE, E_BIT_PACKED = 0, 1, 2, 3, 4
E_RLE_DICT = 8
# codecs
C_UNCOMPRESSED, C_SNAPPY, C_GZIP = 0, 1, 2
# converted types
CT_UTF8, CT_DECIMAL, CT_DATE = 0, 5, 6
CT_TS_MILLIS, CT_TS_MICROS = 9, 10


@dataclasses.dataclass
class ParquetColumn:
    name: str
    type: T.Type
    physical: int
    converted: Optional[int]
    optional: bool
    scale: int = 0
    # timestamp unit -> engine micros: multiply by max(m,1), divide by
    # max(-m,1) (millis: 1000, micros: 1, nanos: -1000)
    ts_mult: int = 1


@dataclasses.dataclass
class ChunkInfo:
    offset: int                  # first page offset (dict page if any)
    total_size: int
    codec: int
    num_values: int
    min_val: Optional[object] = None
    max_val: Optional[object] = None
    null_count: Optional[int] = None


@dataclasses.dataclass
class RowGroupInfo:
    num_rows: int
    chunks: Dict[str, ChunkInfo]


def _engine_type(el: Dict[int, object]) -> Tuple[T.Type, int]:
    # SchemaElement fields (parquet.thrift): 1 type, 3 repetition,
    # 4 name, 6 converted_type, 7 scale, 8 precision, 10 logicalType
    phys = el.get(1)
    conv = el.get(6)
    scale = el.get(7, 0)
    precision = el.get(8, 0)
    logical = el.get(10) or {}
    if conv == CT_DECIMAL and phys in (P_INT32, P_INT64):
        return T.DecimalType(precision or 18, scale or 0), scale or 0
    if phys == P_BOOLEAN:
        return T.BOOLEAN, 0
    if phys == P_INT32:
        if conv == CT_DATE or 6 in logical:
            return T.DATE, 0
        return T.INTEGER, 0
    if phys == P_INT64:
        if conv in (CT_TS_MILLIS, CT_TS_MICROS) or 8 in logical:
            return T.TIMESTAMP, 0
        if 6 in logical:      # logical-only DATE on int64 (unusual)
            return T.DATE, 0
        return T.BIGINT, 0
    if phys in (P_FLOAT, P_DOUBLE):
        return T.DOUBLE, 0
    if phys == P_BYTE_ARRAY:
        return T.VARCHAR, 0
    raise NotImplementedError(f"parquet physical type {phys}")


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid: host header scan + device expansion
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HybridRuns:
    """Flat per-run decode parameters (device-uploadable)."""

    out_start: np.ndarray        # int64[r]
    is_packed: np.ndarray        # bool[r]
    values: np.ndarray           # int64[r]   RLE value
    bit_start: np.ndarray        # int64[r]   absolute payload bit offset


def scan_hybrid(data: bytes, n: int, width: int,
                pos: int = 0) -> Tuple[HybridRuns, int]:
    """Host scan of RLE/bit-packed hybrid run headers: O(runs)."""
    out_start: List[int] = []
    packed: List[bool] = []
    values: List[int] = []
    bit_start: List[int] = []
    nbytes = (width + 7) // 8
    out = 0
    while out < n:
        header, pos = tc._varint(data, pos)
        if header & 1:                     # bit-packed group
            count = (header >> 1) * 8
            out_start.append(out)
            packed.append(True)
            values.append(0)
            bit_start.append(pos * 8)
            pos += (count * width) // 8
            out += count                   # may exceed n (padding group)
        else:                              # RLE run
            count = header >> 1
            v = int.from_bytes(data[pos:pos + nbytes], "little")
            pos += nbytes
            out_start.append(out)
            packed.append(False)
            values.append(v)
            bit_start.append(0)
            out += count
    return HybridRuns(
        out_start=np.asarray(out_start or [0], dtype=np.int64),
        is_packed=np.asarray(packed or [False], dtype=bool),
        values=np.asarray(values or [0], dtype=np.int64),
        bit_start=np.asarray(bit_start or [0], dtype=np.int64),
    ), pos


import functools


@functools.partial(jax.jit, static_argnames=("width", "cap"))
def _expand_hybrid(stream: jnp.ndarray, out_start: jnp.ndarray,
                   is_packed: jnp.ndarray, values: jnp.ndarray,
                   bit_start: jnp.ndarray, width: int,
                   cap: int) -> jnp.ndarray:
    """Device expansion: j -> its run via searchsorted, then either the
    run's RLE value or an LSB-first bit-gather from the raw page bytes
    (the TPU form of RunLengthBitPackingHybridDecoder's inner loop)."""
    j = jnp.arange(cap, dtype=jnp.int64)
    run = jnp.clip(jnp.searchsorted(out_start, j, side="right") - 1,
                   0, out_start.shape[0] - 1)
    rel = j - jnp.take(out_start, run)
    bit = jnp.take(bit_start, run) + rel * width
    byte0 = bit >> 3
    shift = (bit & 7).astype(jnp.int64)
    acc = jnp.zeros(cap, dtype=jnp.int64)
    for k in range(5):                      # width <= 32 spans <= 5 bytes
        b = jnp.take(stream, jnp.clip(byte0 + k, 0, stream.shape[0] - 1),
                     axis=0).astype(jnp.int64)
        acc = acc | (b << (8 * k))
    mask = (jnp.int64(1) << width) - 1 if width < 63 else jnp.int64(-1)
    unpacked = (acc >> shift) & mask
    return jnp.where(jnp.take(is_packed, run), unpacked,
                     jnp.take(values, run))


def decode_hybrid_device(data: bytes, n: int, width: int, cap: int,
                         pos: int = 0) -> jnp.ndarray:
    if width == 0:
        return jnp.zeros(cap, dtype=jnp.int64)
    runs, _ = scan_hybrid(data, n, width, pos)
    scap = bucket_capacity(len(data) + 8, minimum=256)
    stream = np.zeros(scap, dtype=np.uint8)
    stream[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    rcap = bucket_capacity(len(runs.out_start), minimum=16)

    def pad(a, fill=0):
        out = np.full(rcap, fill, dtype=a.dtype)
        out[:len(a)] = a
        return jnp.asarray(out)

    return _expand_hybrid(
        jnp.asarray(stream),
        pad(runs.out_start, fill=np.iinfo(np.int64).max),
        pad(runs.is_packed), pad(runs.values), pad(runs.bit_start),
        width, cap)


def _bitwidth(v: int) -> int:
    return max(int(v).bit_length(), 1) if v > 0 else 1


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

class ParquetReader:
    """One file: parsed footer + per-row-group device decode."""

    def __init__(self, path: str):
        self.path = path
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(size - 8, 0))
            tail = f.read(8)
            if tail[4:] != MAGIC:
                raise ValueError(f"{path}: not a parquet file")
            meta_len = struct.unpack("<I", tail[:4])[0]
            f.seek(size - 8 - meta_len)
            meta = f.read(meta_len)
        fm, _ = tc.read_struct(meta)
        elements = fm[2]
        self.num_rows = fm.get(3, 0)
        self.columns: List[ParquetColumn] = []
        root = elements[0]
        n_children = root.get(5, 0)
        if n_children != len(elements) - 1:
            raise NotImplementedError("nested parquet schemas")
        for el in elements[1:]:
            typ, scale = _engine_type(el)
            name = el[4].decode() if isinstance(el[4], bytes) else el[4]
            conv = el.get(6)
            ts_mult = 1
            if typ is T.TIMESTAMP:
                if conv == CT_TS_MILLIS:
                    ts_mult = 1000
                else:
                    ts = (el.get(10) or {}).get(8) or {}
                    unit = ts.get(2) or {}
                    if 1 in unit:            # MILLIS
                        ts_mult = 1000
                    elif 3 in unit:          # NANOS
                        ts_mult = -1000
            self.columns.append(ParquetColumn(
                name=name, type=typ, physical=el.get(1),
                converted=conv,
                optional=el.get(3, 0) == 1, scale=scale,
                ts_mult=ts_mult))
        self.schema = Schema([(c.name, c.type) for c in self.columns])
        self.row_groups: List[RowGroupInfo] = []
        for rg in fm.get(4, ()):
            chunks: Dict[str, ChunkInfo] = {}
            for cc in rg[1]:
                md = cc[3]
                path_in_schema = [
                    p.decode() if isinstance(p, bytes) else p
                    for p in md[3]]
                name = path_in_schema[0]
                offset = md.get(11) or md[9]
                stats = md.get(12) or {}
                col = next(c for c in self.columns if c.name == name)
                mn, mx = _decode_stat(stats, col)
                chunks[name] = ChunkInfo(
                    offset=offset, total_size=md[7], codec=md[4],
                    num_values=md[5], min_val=mn, max_val=mx,
                    null_count=stats.get(3))
            self.row_groups.append(RowGroupInfo(num_rows=rg[3],
                                                chunks=chunks))

    # -- pruning -------------------------------------------------------------
    def _group_matches(self, rg: RowGroupInfo, pushdown) -> bool:
        """Row-group min/max pruning (reference
        TupleDomainParquetPredicate.java matches())."""
        if not pushdown:
            return True
        for name, lo, hi in pushdown:
            ch = rg.chunks.get(name)
            if ch is None or ch.min_val is None or ch.max_val is None:
                continue
            if lo is not None and ch.max_val < lo:
                return False
            if hi is not None and ch.min_val > hi:
                return False
        return True

    # -- decode --------------------------------------------------------------
    def batches(self, columns: Sequence[str], pushdown=None,
                ) -> Iterator[Batch]:
        """One device batch per surviving row group."""
        want = [next(c for c in self.columns if c.name == n)
                for n in columns]
        schema = Schema([(c.name, c.type) for c in want])
        with open(self.path, "rb") as f:
            for rg in self.row_groups:
                if not self._group_matches(rg, pushdown):
                    continue
                n = rg.num_rows
                cap = bucket_capacity(max(n, 1))
                cols = []
                for c in want:
                    ch = rg.chunks[c.name]
                    f.seek(ch.offset)
                    raw = f.read(ch.total_size)
                    cols.append(self._decode_chunk(c, ch, raw, n, cap))
                mask = jnp.asarray(np.arange(cap) < n)
                yield Batch(schema, cols, mask)

    def _decode_chunk(self, col: ParquetColumn, ch: ChunkInfo,
                      raw: bytes, n_rows: int, cap: int) -> Column:
        pos = 0
        dict_values: Optional[np.ndarray] = None
        dict_vocab: Optional[Tuple[str, ...]] = None
        parts: List[Tuple[int, np.ndarray, object]] = []
        # [(num_values, present, values-or-indices info)]
        total = 0
        while total < ch.num_values and pos < len(raw):
            header, pos = tc.read_struct(raw, pos)
            ptype = header[1]
            comp_size = header[3]
            payload = raw[pos:pos + comp_size]
            pos += comp_size
            if ch.codec == C_GZIP:
                payload = zlib.decompress(payload, 16 + 15)
            elif ch.codec != C_UNCOMPRESSED:
                raise NotImplementedError(
                    f"parquet codec {ch.codec} (use UNCOMPRESSED or GZIP)")
            if ptype == 2:              # dictionary page
                dph = header[7]
                dict_values, dict_vocab = _decode_dict_page(
                    col, payload, dph[1])
                continue
            if ptype != 0:
                raise NotImplementedError(f"page type {ptype}")
            dh = header[5]
            num_values = dh[1]
            encoding = dh[2]
            present, vpos = _decode_def_levels(col, payload, num_values)
            n_present = int(present.sum()) if present is not None \
                else num_values
            parts.append((num_values, present,
                          (encoding, payload, vpos, n_present)))
            total += num_values
        return _assemble_column(col, parts, dict_values, dict_vocab,
                                n_rows, cap)


def _decode_stat(stats: Dict[int, object], col: ParquetColumn):
    def dec(b):
        if b is None:
            return None
        if col.physical == P_INT32:
            return struct.unpack("<i", b)[0]
        if col.physical == P_INT64:
            return struct.unpack("<q", b)[0]
        if col.physical == P_DOUBLE:
            return struct.unpack("<d", b)[0]
        if col.physical == P_FLOAT:
            return struct.unpack("<f", b)[0]
        return None
    # prefer min_value/max_value (field 6/5) over deprecated min/max (2/1)
    mn = dec(stats.get(6, stats.get(2)))
    mx = dec(stats.get(5, stats.get(1)))
    if col.ts_mult != 1:
        # stats are in the file's physical timestamp unit; convert to the
        # engine's micros exactly like _storage_fix converts values (the
        # floor in nanos->micros is monotonic, so converted stats remain
        # valid bounds for converted data)
        if mn is not None:
            mn = int(_storage_fix(col, np.asarray([mn], dtype=np.int64))[0])
        if mx is not None:
            mx = int(_storage_fix(col, np.asarray([mx], dtype=np.int64))[0])
    return mn, mx


def _decode_def_levels(col: ParquetColumn, payload: bytes,
                       num_values: int):
    """V1 data page definition levels -> (present bool[n] | None, pos)."""
    if not col.optional:
        return None, 0
    ln = struct.unpack("<I", payload[:4])[0]
    levels = _decode_hybrid_numpy(payload[4:4 + ln], num_values, 1)
    return levels.astype(bool), 4 + ln


def _decode_hybrid_numpy(data: bytes, n: int, width: int) -> np.ndarray:
    """Host hybrid decode (small streams: def levels)."""
    runs, _ = scan_hybrid(data, n, width, 0)
    out = np.zeros(n, dtype=np.int64)
    arr = np.frombuffer(data, dtype=np.uint8)
    starts = runs.out_start
    for i in range(len(starts)):
        start = int(starts[i])
        end = int(starts[i + 1]) if i + 1 < len(starts) else n
        end = min(end, n)
        if start >= n:
            break
        if not runs.is_packed[i]:
            out[start:end] = runs.values[i]
            continue
        bit0 = int(runs.bit_start[i])
        idx = np.arange(end - start, dtype=np.int64)
        bit = bit0 + idx * width
        acc = np.zeros(end - start, dtype=np.int64)
        for k in range(5):
            byte_idx = np.clip(bit // 8 + k, 0, len(arr) - 1)
            acc |= arr[byte_idx].astype(np.int64) << (8 * k)
        out[start:end] = (acc >> (bit % 8)) & ((1 << width) - 1)
    return out


def _decode_dict_page(col: ParquetColumn, payload: bytes, n: int):
    """PLAIN dictionary page -> (numeric values | None, vocab | None)."""
    if col.physical == P_BYTE_ARRAY:
        vocab: List[str] = []
        pos = 0
        for _ in range(n):
            ln = struct.unpack("<I", payload[pos:pos + 4])[0]
            pos += 4
            vocab.append(payload[pos:pos + ln].decode("utf-8", "replace"))
            pos += ln
        return None, tuple(vocab)
    return _storage_fix(col, np.asarray(_plain_values(col, payload, n))), None


def _plain_values(col: ParquetColumn, payload: bytes, n: int) -> np.ndarray:
    if col.physical == P_INT32:
        return np.frombuffer(payload, dtype="<i4", count=n)
    if col.physical == P_INT64:
        return np.frombuffer(payload, dtype="<i8", count=n)
    if col.physical == P_DOUBLE:
        return np.frombuffer(payload, dtype="<f8", count=n)
    if col.physical == P_FLOAT:
        return np.frombuffer(payload, dtype="<f4", count=n).astype("<f8")
    if col.physical == P_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(payload, dtype=np.uint8),
                             bitorder="little")
        return bits[:n].astype(np.int8)
    raise NotImplementedError(f"PLAIN physical {col.physical}")


def _storage_fix(col: ParquetColumn, arr):
    """Physical -> engine storage adjustments (timestamp units)."""
    if col.ts_mult > 1:
        return arr * col.ts_mult
    if col.ts_mult < -1:
        return arr // (-col.ts_mult)
    return arr


def _assemble_column(col: ParquetColumn, parts, dict_values, dict_vocab,
                     n_rows: int, cap: int) -> Column:
    """Fuse page parts into one device column of ``cap`` slots."""
    out_dtype = col.type.storage_dtype
    validity = np.zeros(cap, dtype=bool)
    row0 = 0
    value_arrays: List[jnp.ndarray] = []
    present_all = np.zeros(cap, dtype=bool)
    # ONE vocabulary per chunk, seeded from the dictionary page: PLAIN
    # fallback pages after a dictionary page (parquet-mr's dictionary
    # overflow layout) and multi-page PLAIN columns append to it, so codes
    # from earlier pages stay valid
    vocab: List[str] = list(dict_vocab or ())
    lookup: Dict[str, int] = {s: i for i, s in enumerate(vocab)}
    for num_values, present, (encoding, payload, vpos, n_present) in parts:
        if present is None:
            present_all[row0:row0 + num_values] = True
        else:
            present_all[row0:row0 + num_values] = present
        if encoding in (E_PLAIN_DICT, E_RLE_DICT):
            width = payload[vpos]
            vcap = bucket_capacity(max(n_present, 1))
            idx = decode_hybrid_device(payload, n_present, width, vcap,
                                       pos=vpos + 1)[:n_present]
            if dict_vocab is not None:
                value_arrays.append(idx.astype(jnp.int32))
            else:
                table = jnp.asarray(dict_values)
                vals = jnp.take(table, jnp.clip(idx, 0, len(dict_values) - 1))
                value_arrays.append(vals)
        elif encoding == E_PLAIN:
            if col.physical == P_BYTE_ARRAY:
                # slow path: host-parsed strings -> shared chunk vocab
                p = vpos
                codes = np.empty(n_present, dtype=np.int32)
                for i in range(n_present):
                    ln = struct.unpack("<I", payload[p:p + 4])[0]
                    p += 4
                    s = payload[p:p + ln].decode("utf-8", "replace")
                    p += ln
                    code = lookup.get(s)
                    if code is None:
                        code = lookup[s] = len(vocab)
                        vocab.append(s)
                    codes[i] = code
                value_arrays.append(jnp.asarray(codes))
            elif col.physical == P_BOOLEAN:
                arr = _plain_values(col, payload[vpos:], n_present)
                value_arrays.append(jnp.asarray(arr))
            else:
                arr = _plain_values(col, payload[vpos:], n_present)
                value_arrays.append(jnp.asarray(
                    _storage_fix(col, np.asarray(arr))))
        else:
            raise NotImplementedError(f"parquet encoding {encoding}")
        row0 += num_values
    if col.physical == P_BYTE_ARRAY:
        dict_vocab = tuple(vocab)
    validity[:] = present_all
    if value_arrays:
        flat = jnp.concatenate([v.reshape(-1) for v in value_arrays]) \
            if len(value_arrays) > 1 else value_arrays[0]
    else:
        flat = jnp.zeros(1, dtype=out_dtype)
    if flat.shape[0] == 0:      # entirely-NULL chunk: pages carry 0 values
        flat = jnp.zeros(1, dtype=out_dtype)
    # scatter present values to row slots: row j takes the k-th value
    # where k = rank of j among present rows
    presj = jnp.asarray(present_all)
    rank = prefix_sum(presj.astype(jnp.int32)) - 1
    gathered = jnp.take(flat.astype(out_dtype),
                        jnp.clip(rank, 0, flat.shape[0] - 1), axis=0)
    data = jnp.where(presj, gathered, jnp.zeros_like(gathered))
    return Column(col.type, data, jnp.asarray(validity), dict_vocab)


# ---------------------------------------------------------------------------
# Writer (test fixtures + CTAS export): single row group, V1 pages,
# PLAIN numerics / PLAIN_DICTIONARY strings, UNCOMPRESSED
# ---------------------------------------------------------------------------

def write_parquet(path: str, schema: Schema,
                  columns: Sequence[Sequence[object]]) -> None:
    """Write python column values (None = NULL) as a flat parquet file."""
    n = len(columns[0]) if columns else 0
    out = bytearray(MAGIC)
    chunk_metas: List[bytes] = []
    for (name, typ), values in zip(
            [(f.name, f.type) for f in schema.fields], columns):
        phys, conv = _physical_of(typ)
        offset = len(out)
        present = [v is not None for v in values]
        dict_page_offset = None
        if typ.is_string:
            vocab: List[str] = []
            lookup: Dict[str, int] = {}
            idx: List[int] = []
            for v in values:
                if v is None:
                    continue
                code = lookup.get(v)
                if code is None:
                    code = lookup[v] = len(vocab)
                    vocab.append(v)
                idx.append(code)
            dict_payload = bytearray()
            for s in vocab:
                b = s.encode()
                dict_payload += struct.pack("<I", len(b)) + b
            dict_page_offset = len(out)
            out += _page_header(2, len(dict_payload), dict_n=len(vocab))
            out += dict_payload
            width = _bitwidth(max(len(vocab) - 1, 1))
            payload = _def_levels(present) + bytes([width]) \
                + _rle_encode(idx, width)
            data_page_offset = len(out)
            out += _page_header(0, len(payload), data_n=n,
                                encoding=E_PLAIN_DICT)
            out += payload
        else:
            payload = _def_levels(present) + _plain_encode(
                typ, phys, [v for v in values if v is not None])
            data_page_offset = len(out)
            out += _page_header(0, len(payload), data_n=n,
                                encoding=E_PLAIN)
            out += payload
        total = len(out) - offset
        md = tc.write_struct([
            (1, tc.I32, phys),
            (2, tc.LIST, (tc.I32, [E_PLAIN, E_RLE, E_PLAIN_DICT])),
            (3, tc.LIST, (tc.BINARY, [name])),
            (4, tc.I32, C_UNCOMPRESSED),
            (5, tc.I64, n),
            (6, tc.I64, total),
            (7, tc.I64, total),
            (9, tc.I64, data_page_offset),
            (11, tc.I64, dict_page_offset),
            (12, tc.STRUCT, _stats_struct(typ, phys, values)),
        ])
        chunk_metas.append(tc.write_struct([
            (2, tc.I64, offset),
            (3, tc.STRUCT, md),
        ]))
    rg = tc.write_struct([
        (1, tc.LIST, (tc.STRUCT, chunk_metas)),
        (2, tc.I64, len(out) - 4),
        (3, tc.I64, n),
    ])
    elements = [tc.write_struct([
        (4, tc.BINARY, "schema"),
        (5, tc.I32, len(schema)),
    ])]
    for f in schema.fields:
        phys, conv = _physical_of(f.type)
        fields = [(1, tc.I32, phys), (3, tc.I32, 1),
                  (4, tc.BINARY, f.name)]
        if conv is not None:
            fields.append((6, tc.I32, conv))
        if isinstance(f.type, T.DecimalType):
            fields.append((7, tc.I32, f.type.scale))
            fields.append((8, tc.I32, f.type.precision))
        elements.append(tc.write_struct(fields))
    meta = tc.write_struct([
        (1, tc.I32, 1),
        (2, tc.LIST, (tc.STRUCT, elements)),
        (3, tc.I64, n),
        (4, tc.LIST, (tc.STRUCT, [rg])),
    ])
    out += meta
    out += struct.pack("<I", len(meta))
    out += MAGIC
    with open(path, "wb") as f:
        f.write(bytes(out))


def _physical_of(typ: T.Type) -> Tuple[int, Optional[int]]:
    if isinstance(typ, T.BooleanType):
        return P_BOOLEAN, None
    if isinstance(typ, T.DateType):
        return P_INT32, CT_DATE
    if isinstance(typ, (T.TinyintType, T.SmallintType, T.IntegerType)):
        return P_INT32, None
    if isinstance(typ, T.TimestampType):
        return P_INT64, CT_TS_MICROS
    if isinstance(typ, T.DecimalType):
        return P_INT64, CT_DECIMAL
    if isinstance(typ, T.BigintType):
        return P_INT64, None
    if isinstance(typ, (T.DoubleType, T.RealType)):
        return P_DOUBLE, None
    if typ.is_string:
        return P_BYTE_ARRAY, CT_UTF8
    raise NotImplementedError(f"parquet write of {typ.display()}")


def _page_header(ptype: int, size: int, data_n: int = 0,
                 dict_n: int = 0, encoding: int = E_PLAIN) -> bytes:
    if ptype == 2:
        inner = tc.write_struct([(1, tc.I32, dict_n),
                                 (2, tc.I32, E_PLAIN)])
        return tc.write_struct([
            (1, tc.I32, 2), (2, tc.I32, size), (3, tc.I32, size),
            (7, tc.STRUCT, inner)])
    inner = tc.write_struct([
        (1, tc.I32, data_n), (2, tc.I32, encoding),
        (3, tc.I32, E_RLE), (4, tc.I32, E_RLE)])
    return tc.write_struct([
        (1, tc.I32, 0), (2, tc.I32, size), (3, tc.I32, size),
        (5, tc.STRUCT, inner)])


def _def_levels(present: List[bool]) -> bytes:
    body = _rle_encode([1 if p else 0 for p in present], 1)
    return struct.pack("<I", len(body)) + body


def _rle_encode(values: List[int], width: int) -> bytes:
    """Pure RLE runs (always valid hybrid encoding)."""
    out = bytearray()
    nbytes = (width + 7) // 8
    i = 0
    while i < len(values):
        j = i
        while j < len(values) and values[j] == values[i]:
            j += 1
        out += tc._w_varint((j - i) << 1)
        out += int(values[i]).to_bytes(nbytes, "little")
        i = j
    return bytes(out)


def _plain_encode(typ: T.Type, phys: int, values: List[object]) -> bytes:
    storage = [typ.to_storage(v) for v in values]
    if phys == P_INT32:
        return np.asarray(storage, dtype="<i4").tobytes()
    if phys == P_INT64:
        return np.asarray(storage, dtype="<i8").tobytes()
    if phys == P_DOUBLE:
        return np.asarray(storage, dtype="<f8").tobytes()
    if phys == P_BOOLEAN:
        bits = np.asarray(storage, dtype=np.uint8)
        return np.packbits(bits, bitorder="little").tobytes()
    raise NotImplementedError(f"plain encode {phys}")


def _stats_struct(typ: T.Type, phys: int, values) -> Optional[bytes]:
    live = [typ.to_storage(v) for v in values if v is not None]
    if not live or phys not in (P_INT32, P_INT64, P_DOUBLE):
        return None
    mn, mx = min(live), max(live)
    fmt = {P_INT32: "<i", P_INT64: "<q", P_DOUBLE: "<d"}[phys]
    return tc.write_struct([
        (3, tc.I64, sum(1 for v in values if v is None)),
        (5, tc.BINARY, struct.pack(fmt, mx)),
        (6, tc.BINARY, struct.pack(fmt, mn)),
    ])

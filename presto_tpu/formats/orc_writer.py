"""ORC file writer: spec-conformant stripes + protobuf tail.

The write-side sibling of formats/orc.py (reference presto-orc/src/main/
java/io/prestosql/orc/writer/ — StripeReader's counterpart OrcWriter.java,
ColumnWriters, metadata serializers). Encodings chosen for simplicity and
reader coverage:

- int family / date:  RLEv2 DIRECT runs (zigzag for signed)
- double/float:       raw little-endian IEEE
- string/varchar:     DIRECT_V2 (utf-8 blob + RLEv2 length stream)
- boolean:            bit-packed over byte-RLE
- nulls:              PRESENT stream (bit-packed over byte-RLE)
- compression:        NONE (postscript declares it; readers honor it)

File/stripe integer statistics (min/max/hasNull) are emitted so readers
prune files and stripes (reference TupleDomainOrcPredicate.java:77);
verified round-trip against pyarrow.orc in tests.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Schema
from .orc_rle import _WIDTH_TABLE

MAGIC = b"ORC"

# engine type -> (orc kind code, orc kind name)
_KIND_BOOL, _KIND_BYTE, _KIND_SHORT, _KIND_INT, _KIND_LONG = 0, 1, 2, 3, 4
_KIND_FLOAT, _KIND_DOUBLE, _KIND_STRING = 5, 6, 7
_KIND_STRUCT, _KIND_DECIMAL, _KIND_DATE = 12, 14, 15
_KIND_VARCHAR, _KIND_CHAR = 16, 17


# -- protobuf wire writing ---------------------------------------------------

def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def _field_varint(field: int, v: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(v)


def _field_bytes(field: int, b: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(b)) + b


# -- stream encoders ---------------------------------------------------------

#: DIRECT runs must use ALIGNED widths (ORC spec; the C++ reader decodes
#: unaligned DIRECT widths as their aligned round-up, silently corrupting
#: values — verified against pyarrow)
_ALIGNED_WIDTHS = (1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64)


def _closest_width(bits: int) -> int:
    for w in _ALIGNED_WIDTHS:
        if w >= bits:
            return w
    return 64


def rle_v2_encode(values: np.ndarray, signed: bool) -> bytes:
    """DIRECT-run RLEv2: runs of <=512 values, per-run width from the
    run's max magnitude (reference RunLengthIntegerWriterV2 DIRECT
    mode)."""
    vals = values.astype(np.int64)
    if signed:
        enc = (vals.astype(np.uint64) << np.uint64(1)) ^ \
            (vals >> np.int64(63)).astype(np.uint64)
    else:
        enc = vals.astype(np.uint64)
    out = bytearray()
    for start in range(0, len(enc), 512):
        run = enc[start:start + 512]
        count = len(run)
        mx = int(run.max()) if count else 0
        width = _closest_width(max(int(mx).bit_length(), 1))
        wcode = _WIDTH_TABLE.index(width)
        header = (1 << 6) | (wcode << 1) | ((count - 1) >> 8)
        out.append(header)
        out.append((count - 1) & 0xFF)
        acc = 0
        for v in run.tolist():
            acc = (acc << width) | int(v)
        total_bits = count * width
        pad = (-total_bits) % 8
        acc <<= pad
        out += int(acc).to_bytes((total_bits + pad) // 8, "big")
    return bytes(out)


def byte_rle_encode(raw: bytes) -> bytes:
    """ORC byte-RLE (reference stream/ByteOutputStream.java): repeat runs
    of 3..130 as (count-3, byte); literal groups of <=127 as
    (256-count, bytes)."""
    out = bytearray()
    i, n = 0, len(raw)
    lit_start = i
    while i < n:
        run = 1
        while i + run < n and run < 130 and raw[i + run] == raw[i]:
            run += 1
        if run >= 3:
            while lit_start < i:
                take = min(127, i - lit_start)
                out.append(256 - take)
                out += raw[lit_start:lit_start + take]
                lit_start += take
            out.append(run - 3)
            out.append(raw[i])
            i += run
            lit_start = i
        else:
            i += run
    while lit_start < i:
        take = min(127, i - lit_start)
        out.append(256 - take)
        out += raw[lit_start:lit_start + take]
        lit_start += take
    return bytes(out)


def present_encode(validity: np.ndarray) -> bytes:
    return byte_rle_encode(np.packbits(validity.astype(np.uint8))
                           .tobytes())


# -- column serialization ----------------------------------------------------

def _orc_kind(t: T.Type) -> int:
    if isinstance(t, T.BooleanType):
        return _KIND_BOOL
    if isinstance(t, T.TinyintType):
        return _KIND_BYTE
    if isinstance(t, T.SmallintType):
        return _KIND_SHORT
    if isinstance(t, T.IntegerType):
        return _KIND_INT
    if isinstance(t, T.BigintType):
        return _KIND_LONG
    if isinstance(t, T.DoubleType):
        return _KIND_DOUBLE
    if isinstance(t, T.DateType):
        return _KIND_DATE
    if isinstance(t, T.DecimalType):
        return _KIND_DECIMAL
    if t.is_string:
        return _KIND_STRING
    raise NotImplementedError(
        f"ORC writer does not support {t.display()}")


def _svarint(v: int) -> bytes:
    return _varint(_zigzag(v))


@dataclasses.dataclass
class _ColumnAccum:
    """Host row accumulator for one column across a stripe."""

    type: T.Type
    values: List[np.ndarray] = dataclasses.field(default_factory=list)
    validity: List[np.ndarray] = dataclasses.field(default_factory=list)
    strings: List[List[Optional[str]]] = dataclasses.field(
        default_factory=list)

    def add(self, col, mask: np.ndarray) -> None:
        valid = np.asarray(col.validity)[mask]
        self.validity.append(valid)
        if self.type.is_string:
            codes = np.asarray(col.data)[mask]
            vocab = col.dictionary or ()
            self.strings.append([
                vocab[c] if v and 0 <= c < len(vocab) else None
                for c, v in zip(codes.tolist(), valid.tolist())])
        else:
            self.values.append(np.asarray(col.data)[mask])


def _encode_column(acc: _ColumnAccum) -> Tuple[
        Dict[str, bytes], Optional[Tuple[int, int]], bool, int]:
    """-> (streams, int min/max or None, has_null, n_values)"""
    validity = (np.concatenate(acc.validity) if acc.validity
                else np.zeros(0, dtype=bool))
    has_null = bool((~validity).any())
    streams: Dict[str, bytes] = {}
    if has_null:
        streams["present"] = present_encode(validity)
    stats = None
    if acc.type.is_string:
        rows = [s for chunk in acc.strings for s in chunk]
        present = [s for s in rows if s is not None]
        blobs = [s.encode("utf-8") for s in present]
        streams["data"] = b"".join(blobs)
        streams["length"] = rle_v2_encode(
            np.asarray([len(b) for b in blobs] or [0],
                       dtype=np.int64)[:len(blobs)], signed=False)
        n_values = len(present)
        return streams, None, has_null, n_values
    vals = (np.concatenate(acc.values) if acc.values
            else np.zeros(0, dtype=np.int64))
    live = vals[validity]
    n_values = len(live)
    if isinstance(acc.type, T.DecimalType):
        # ORC decimal: DATA = zigzag base-128 varint unscaled values,
        # SECONDARY = per-value scale as signed RLE (reference
        # presto-orc/.../stream/DecimalInputStream.java)
        streams["data"] = b"".join(_svarint(int(v))
                                   for v in live.tolist())
        streams["secondary"] = rle_v2_encode(
            np.full(n_values, acc.type.scale, dtype=np.int64),
            signed=True)
        return streams, None, has_null, n_values
    if isinstance(acc.type, T.DoubleType):
        streams["data"] = live.astype("<f8").tobytes()
    elif isinstance(acc.type, T.BooleanType):
        streams["data"] = byte_rle_encode(
            np.packbits(live.astype(np.uint8)).tobytes())
    elif isinstance(acc.type, T.TinyintType):
        streams["data"] = byte_rle_encode(
            live.astype(np.int8).tobytes())
        if n_values:
            stats = (int(live.min()), int(live.max()))
    else:
        streams["data"] = rle_v2_encode(live.astype(np.int64),
                                        signed=True)
        if n_values:
            stats = (int(live.min()), int(live.max()))
    return streams, stats, has_null, n_values


def _column_stats_pb(n_values: int, stats: Optional[Tuple[int, int]],
                     has_null: bool) -> bytes:
    msg = _field_varint(1, n_values)
    if stats is not None:
        ints = (_field_varint(1, _zigzag(stats[0]))
                + _field_varint(2, _zigzag(stats[1])))
        msg += _field_bytes(2, ints)
    msg += _field_varint(10, 1 if has_null else 0)
    return msg


class OrcWriter:
    """Streaming ORC writer: batches in, stripes out every
    ``stripe_rows`` rows (reference writer/OrcWriter.java flush
    policy)."""

    def __init__(self, path: str, schema: Schema,
                 stripe_rows: int = 1 << 16):
        self.path = path
        self.schema = schema
        self.stripe_rows = stripe_rows
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._offset = len(MAGIC)
        self._accums = [_ColumnAccum(t) for t in schema.types]
        self._accum_rows = 0
        self._total_rows = 0
        self._stripe_infos: List[Tuple[int, int, int, int]] = []
        # (offset, data_len, footer_len, rows)
        self._stripe_stats: List[List[bytes]] = []
        self._file_stats: List[Tuple[
            int, Optional[Tuple[int, int]], bool]] = [
            (0, None, False) for _ in schema.types]

    # -- ingest --------------------------------------------------------------
    def write_batch(self, batch: Batch) -> int:
        mask = np.asarray(batch.row_mask)
        rows = np.nonzero(mask)[0]
        n = len(rows)
        if n == 0:
            return 0
        # chunk so no stripe exceeds stripe_rows
        start = 0
        while start < n:
            room = self.stripe_rows - self._accum_rows
            take = rows[start:start + room]
            sub = np.zeros_like(mask)
            sub[take] = True
            for acc, col in zip(self._accums, batch.columns):
                acc.add(col, sub)
            self._accum_rows += len(take)
            self._total_rows += len(take)
            start += len(take)
            if self._accum_rows >= self.stripe_rows:
                self._flush_stripe()
        return n

    # -- stripe / tail -------------------------------------------------------
    def _flush_stripe(self) -> None:
        if self._accum_rows == 0:
            return
        stream_list: List[Tuple[int, str, bytes]] = []
        col_stats_pb: List[bytes] = [
            _column_stats_pb(self._accum_rows, None, False)]
        for ci, acc in enumerate(self._accums):
            streams, stats, has_null, n_values = _encode_column(acc)
            for kind in ("present", "data", "length", "secondary"):
                if kind in streams:
                    stream_list.append((ci + 1, kind, streams[kind]))
            col_stats_pb.append(
                _column_stats_pb(n_values, stats, has_null))
            total, fstats, fnull = self._file_stats[ci]
            if stats is not None:
                fstats = (stats if fstats is None else
                          (min(fstats[0], stats[0]),
                           max(fstats[1], stats[1])))
            self._file_stats[ci] = (total + n_values, fstats,
                                    fnull or has_null)

        kind_code = {"present": 0, "data": 1, "length": 2,
                     "secondary": 5}
        footer = b""
        data = b""
        for ci, kind, blob in stream_list:
            data += blob
            s = (_field_varint(1, kind_code[kind])
                 + _field_varint(2, ci)
                 + _field_varint(3, len(blob)))
            footer += _field_bytes(1, s)
        # encodings: DIRECT_V2 wherever an integer RLE stream is involved
        # (plain DIRECT would mean RLE v1 to conformant readers); struct
        # root and streams with no int RLE (bool/byte/double) are DIRECT
        footer += _field_bytes(2, _field_varint(1, 0))
        for t in self.schema.types:
            v1_ok = isinstance(t, (T.BooleanType, T.TinyintType,
                                   T.DoubleType))
            footer += _field_bytes(2, _field_varint(1, 0 if v1_ok else 2))

        self._f.write(data)
        self._f.write(footer)
        self._stripe_infos.append(
            (self._offset, len(data), len(footer), self._accum_rows))
        self._stripe_stats.append(col_stats_pb)
        self._offset += len(data) + len(footer)
        self._accums = [_ColumnAccum(t) for t in self.schema.types]
        self._accum_rows = 0

    def close(self) -> None:
        self._flush_stripe()
        # --- metadata (per-stripe statistics) ---
        metadata = b""
        for col_stats in self._stripe_stats:
            ss = b"".join(_field_bytes(1, cs) for cs in col_stats)
            metadata += _field_bytes(1, ss)
        # --- footer ---
        footer = _field_varint(1, len(MAGIC))       # headerLength
        footer += _field_varint(2, self._offset)    # contentLength
        for off, dlen, flen, rows in self._stripe_infos:
            si = (_field_varint(1, off) + _field_varint(2, 0)
                  + _field_varint(3, dlen) + _field_varint(4, flen)
                  + _field_varint(5, rows))
            footer += _field_bytes(3, si)
        root = _field_varint(1, _KIND_STRUCT)
        for i in range(len(self.schema.types)):
            root += _field_varint(2, i + 1)
        for name in self.schema.names:
            root += _field_bytes(3, name.encode("utf-8"))
        footer += _field_bytes(4, root)
        for t in self.schema.types:
            tb = _field_varint(1, _orc_kind(t))
            if isinstance(t, T.DecimalType):
                tb += _field_varint(5, t.precision)
                tb += _field_varint(6, t.scale)
            footer += _field_bytes(4, tb)
        footer += _field_varint(6, self._total_rows)
        footer += _field_bytes(
            7, _column_stats_pb(self._total_rows, None, False))
        for n_values, stats, has_null in self._file_stats:
            footer += _field_bytes(
                7, _column_stats_pb(n_values, stats, has_null))
        footer += _field_varint(8, 0)               # rowIndexStride
        # --- postscript ---
        ps = (_field_varint(1, len(footer))
              + _field_varint(2, 0)                 # compression NONE
              + _field_varint(3, 256 * 1024)
              + _field_varint(4, 0) + _field_varint(4, 12)  # version 0.12
              + _field_varint(5, len(metadata))
              + _field_varint(6, 1)                 # writer version
              + _field_bytes(8000, MAGIC))
        self._f.write(metadata)
        self._f.write(footer)
        self._f.write(ps)
        self._f.write(bytes([len(ps)]))
        self._f.close()


def write_orc(path: str, schema: Schema, batches,
              stripe_rows: int = 1 << 16) -> int:
    w = OrcWriter(path, schema, stripe_rows)
    n = 0
    for b in batches:
        n += w.write_batch(b)
    w.close()
    return n

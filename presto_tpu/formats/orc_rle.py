"""ORC integer RLEv2 + boolean/byte RLE decoding, TPU-first.

The reference decodes these streams with sequential Java readers
(reference presto-orc/.../stream/LongInputStreamV2.java,
LongBitPacker.java, BooleanInputStream.java, ByteInputStream.java). A
sequential loop is hostile to a vector unit, so the decode splits:

- the HOST scans run headers only (a few bytes per run, data-dependent
  lengths — inherently sequential, but tiny compared to the packed
  payload) into a flat run table;
- the DEVICE expands all runs in one vectorized kernel: every output
  element locates its run by searchsorted, computes its absolute bit
  position, gathers an 8-byte window from the raw stream bytes, and
  shifts/masks its value out — bit-unpacking of the whole column in one
  fused XLA program. DELTA runs resolve through a global cumulative sum
  with per-run carry subtraction. PATCHED_BASE runs (rare) decode on the
  host into an exceptions array the kernel gathers from.

A pure-NumPy reference decoder (`decode_rle_v2_numpy`) provides the
host fallback and the oracle for tests.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.prefix import prefix_sum
from ..batch import bucket_capacity

# 5-bit width code -> bit width (ORC spec "Direct" width encoding)
_WIDTH_TABLE = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]

K_SHORT_REPEAT, K_DIRECT, K_PATCHED, K_DELTA = 0, 1, 2, 3


def _decode_width(code: int) -> int:
    return _WIDTH_TABLE[code]


def _closest_fixed_bits(bits: int) -> int:
    """Round up to the nearest encodable fixed width (ORC spec
    closestFixedBits; reference LongBitPacker widths)."""
    for w in _WIDTH_TABLE:
        if w >= bits:
            return w
    return 64


def _zigzag_np(v):
    return (v >> 1) ^ -(v & 1)


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _read_svarint(data: bytes, pos: int) -> Tuple[int, int]:
    v, pos = _read_varint(data, pos)
    return (v >> 1) ^ -(v & 1), pos


@dataclasses.dataclass
class RunTable:
    """Flat per-run decode parameters (host numpy, device-uploadable)."""

    n: int                       # total output values
    kinds: np.ndarray            # int32[r]
    out_start: np.ndarray        # int64[r]  first output index of run
    bit_start: np.ndarray        # int64[r]  absolute bit offset of payload
    widths: np.ndarray           # int32[r]  payload bit width (0 = none)
    literals: np.ndarray         # int64[r]  short-repeat value / delta base
    delta_bases: np.ndarray      # int64[r]  first delta (signed)
    patch_offset: np.ndarray     # int64[r]  offset into patched values
    patched: np.ndarray          # int64[*]  pre-decoded PATCHED_BASE values
    signed: bool


def scan_rle_v2(data: bytes, n: int, signed: bool) -> RunTable:
    """Sequential header scan (host): O(runs), not O(values)."""
    kinds: List[int] = []
    out_start: List[int] = []
    bit_start: List[int] = []
    widths: List[int] = []
    literals: List[int] = []
    delta_bases: List[int] = []
    patch_offset: List[int] = []
    patched: List[int] = []

    pos = 0
    out = 0
    while out < n and pos < len(data):
        header = data[pos]
        enc = header >> 6
        if enc == 0:                      # SHORT_REPEAT
            width = ((header >> 3) & 7) + 1
            count = (header & 7) + 3
            value = int.from_bytes(data[pos + 1:pos + 1 + width], "big")
            if signed:
                value = _zigzag_np(value)
            kinds.append(K_SHORT_REPEAT)
            out_start.append(out)
            bit_start.append(0)
            widths.append(0)
            literals.append(value)
            delta_bases.append(0)
            patch_offset.append(0)
            pos += 1 + width
            out += count
        elif enc == 1:                    # DIRECT
            width = _decode_width((header >> 1) & 0x1F)
            count = ((header & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            kinds.append(K_DIRECT)
            out_start.append(out)
            bit_start.append(pos * 8)
            widths.append(width)
            literals.append(0)
            delta_bases.append(0)
            patch_offset.append(0)
            pos += (count * width + 7) // 8
            out += count
        elif enc == 3:                    # DELTA
            wcode = (header >> 1) & 0x1F
            width = _decode_width(wcode) if wcode else 0
            count = ((header & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            if signed:
                base, pos = _read_svarint(data, pos)
            else:
                base, pos = _read_varint(data, pos)
            delta_base, pos = _read_svarint(data, pos)
            kinds.append(K_DELTA)
            out_start.append(out)
            bit_start.append(pos * 8)
            widths.append(width)
            literals.append(base)
            delta_bases.append(delta_base)
            patch_offset.append(0)
            if width:
                pos += (max(count - 2, 0) * width + 7) // 8
            out += count
        else:                             # PATCHED_BASE: host decode
            vals, pos = _decode_patched_base(data, pos)
            kinds.append(K_PATCHED)
            out_start.append(out)
            bit_start.append(0)
            widths.append(0)
            literals.append(0)
            delta_bases.append(0)
            patch_offset.append(len(patched))
            patched.extend(int(v) for v in vals)
            out += len(vals)
    if out < n:
        raise ValueError(f"RLEv2 stream exhausted at {out}/{n} values")
    return RunTable(
        n=n,
        kinds=np.asarray(kinds, dtype=np.int32),
        out_start=np.asarray(out_start, dtype=np.int64),
        bit_start=np.asarray(bit_start, dtype=np.int64),
        widths=np.asarray(widths, dtype=np.int32),
        literals=np.asarray(literals, dtype=np.int64),
        delta_bases=np.asarray(delta_bases, dtype=np.int64),
        patch_offset=np.asarray(patch_offset, dtype=np.int64),
        patched=np.asarray(patched or [0], dtype=np.int64),
        signed=signed,
    )


def _unpack_bits_np(data: bytes, bit_pos: int, width: int,
                    count: int) -> np.ndarray:
    """Big-endian bit unpack on host (reference LongBitPacker.java)."""
    out = np.empty(count, dtype=np.int64)
    for i in range(count):
        bp = bit_pos + i * width
        acc = 0
        remaining = width
        while remaining > 0:
            byte = data[bp >> 3]
            avail = 8 - (bp & 7)
            take = min(avail, remaining)
            bits = (byte >> (avail - take)) & ((1 << take) - 1)
            acc = (acc << take) | bits
            bp += take
            remaining -= take
        out[i] = acc
    return out


def _decode_patched_base(data: bytes, pos: int) -> Tuple[np.ndarray, int]:
    header = data[pos]
    width = _decode_width((header >> 1) & 0x1F)
    count = ((header & 1) << 8 | data[pos + 1]) + 1
    third, fourth = data[pos + 2], data[pos + 3]
    base_bytes = ((third >> 5) & 7) + 1
    patch_width = _decode_width(third & 0x1F)
    patch_gap_width = ((fourth >> 5) & 7) + 1
    patch_count = fourth & 0x1F
    pos += 4
    base = int.from_bytes(data[pos:pos + base_bytes], "big")
    sign_mask = 1 << (base_bytes * 8 - 1)
    if base & sign_mask:
        base = -(base & (sign_mask - 1))
    pos += base_bytes
    values = _unpack_bits_np(data, pos * 8, width, count)
    pos += (count * width + 7) // 8
    # patch-list entries are (gap, patch) packed at
    # closestFixedBits(gap_width + patch_width) bits (ORC spec)
    pl_width = _closest_fixed_bits(patch_gap_width + patch_width)
    patches = _unpack_bits_np(data, pos * 8, pl_width, patch_count)
    pos += (patch_count * pl_width + 7) // 8
    idx = 0
    for p in patches:
        gap = int(p) >> patch_width
        patch = int(p) & ((1 << patch_width) - 1)
        idx += gap
        values[idx] |= patch << width
    return values + base, pos


def decode_rle_v2_numpy(data: bytes, n: int, signed: bool) -> np.ndarray:
    """Reference decoder: full host decode (oracle + fallback)."""
    out = np.empty(n, dtype=np.int64)
    rt = scan_rle_v2(data, n, signed)
    r = len(rt.kinds)
    bounds = np.append(rt.out_start, n)
    for i in range(r):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        count = hi - lo
        kind = rt.kinds[i]
        if kind == K_SHORT_REPEAT:
            out[lo:hi] = rt.literals[i]
        elif kind == K_DIRECT:
            vals = _unpack_bits_np(data, int(rt.bit_start[i]),
                                   int(rt.widths[i]), count)
            if signed:
                vals = _zigzag_np(vals)
            out[lo:hi] = vals
        elif kind == K_DELTA:
            base, db = int(rt.literals[i]), int(rt.delta_bases[i])
            vals = np.empty(count, dtype=np.int64)
            vals[0] = base
            if count > 1:
                vals[1] = base + db
            if count > 2:
                w = int(rt.widths[i])
                if w:
                    deltas = _unpack_bits_np(
                        data, int(rt.bit_start[i]), w, count - 2)
                else:
                    deltas = np.full(count - 2, abs(db), dtype=np.int64)
                sign = 1 if db >= 0 else -1
                vals[2:] = vals[1] + sign * np.cumsum(deltas)
            out[lo:hi] = vals
        else:
            po = int(rt.patch_offset[i])
            out[lo:hi] = rt.patched[po:po + count]
    return out


# ---------------------------------------------------------------------------
# Device expansion kernel
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2, 3))
def _expand_runs(stream_u8: jnp.ndarray, table: Tuple[jnp.ndarray, ...],
                 n_cap: int, signed: bool) -> jnp.ndarray:
    (kinds, out_start, bit_start, widths, literals, delta_bases,
     patch_offset, patched, n_runs) = table
    j = jnp.arange(n_cap, dtype=jnp.int64)
    # run of each output element (out_start is padded with +inf-like)
    r = jnp.clip(jnp.searchsorted(out_start, j, side="right") - 1,
                 0, out_start.shape[0] - 1)
    i = j - jnp.take(out_start, r)             # index within the run
    kind = jnp.take(kinds, r)
    width = jnp.take(widths, r).astype(jnp.int64)

    # ---- bit extraction (DIRECT payload / DELTA deltas) ----
    # DELTA payload holds deltas for in-run indices >= 2
    di = jnp.where(kind == K_DELTA, jnp.maximum(i - 2, 0), i)
    bp = jnp.take(bit_start, r) + di * width
    byte0 = bp >> 3
    shift_in = bp & 7
    # gather an 8-byte big-endian window starting at byte0
    offs = jnp.arange(8, dtype=jnp.int64)
    idx = jnp.clip(byte0[:, None] + offs[None, :],
                   0, stream_u8.shape[0] - 1)
    window_bytes = jnp.take(stream_u8, idx, axis=0).astype(jnp.uint64)
    shifts = jnp.uint64(8) * (jnp.uint64(7) - offs.astype(jnp.uint64))
    window = jnp.sum(window_bytes << shifts[None, :], axis=1)
    # value = bits [shift_in, shift_in + width) of the window (max 56 bits)
    shift_out = jnp.clip(64 - shift_in - width, 0, 63).astype(jnp.uint64)
    mask = ((jnp.uint64(1) << jnp.clip(width, 0, 63).astype(jnp.uint64))
            - jnp.uint64(1))
    raw = (window >> shift_out) & mask
    raw = jnp.where(width > 0, raw, jnp.uint64(0)).astype(jnp.int64)

    # ---- DIRECT ----
    direct_val = jnp.where(signed, (raw >> 1) ^ -(raw & 1), raw)

    # ---- DELTA: value(i>=2) = base + delta_base + sign * sum(d_2..d_i).
    # One global cumsum of per-element delta contributions; each element
    # subtracts the cumsum just before its run (exclusive prefix), which
    # cancels all prior runs' contributions.
    db = jnp.take(delta_bases, r)
    sign = jnp.where(db >= 0, 1, -1).astype(jnp.int64)
    dmag = jnp.where(width > 0, raw, jnp.abs(db))
    contrib = jnp.where((kind == K_DELTA) & (i >= 2), sign * dmag, 0)
    cum = prefix_sum(contrib)
    run_first = jnp.clip(jnp.take(out_start, r), 0, n_cap)
    cum_before_run = jnp.take(
        jnp.concatenate([jnp.zeros(1, jnp.int64), cum]), run_first)
    delta_val = (jnp.take(literals, r)
                 + jnp.where(i >= 1, db, 0)
                 + (cum - cum_before_run))

    # ---- SHORT_REPEAT / PATCHED ----
    sr_val = jnp.take(literals, r)
    patched_idx = jnp.clip(jnp.take(patch_offset, r) + i,
                           0, patched.shape[0] - 1)
    patched_val = jnp.take(patched, patched_idx)

    out = jnp.where(kind == K_SHORT_REPEAT, sr_val,
                    jnp.where(kind == K_DIRECT, direct_val,
                              jnp.where(kind == K_DELTA, delta_val,
                                        patched_val)))
    return out


def decode_rle_v2_device(data: bytes, n: int, signed: bool,
                         capacity: Optional[int] = None) -> jnp.ndarray:
    """Decode an RLEv2 stream to int64[capacity] on device.

    Host scans headers; device expands. Output padded to ``capacity``
    (bucketed so kernels recompile only on bucket changes).
    """
    cap = capacity or bucket_capacity(n)
    rt = scan_rle_v2(data, n, signed)
    if np.any(rt.widths > 56):
        # 8-byte window can't span >56 bits + intra-byte shift: fall back
        vals = decode_rle_v2_numpy(data, n, signed)
        out = np.zeros(cap, dtype=np.int64)
        out[:n] = vals
        return jnp.asarray(out)
    n_runs = len(rt.kinds)
    rcap = bucket_capacity(n_runs, minimum=16)

    def pad(a, fill=0):
        out = np.full(rcap, fill, dtype=a.dtype)
        out[:n_runs] = a
        return jnp.asarray(out)

    pcap = bucket_capacity(len(rt.patched), minimum=16)
    patched = np.zeros(pcap, dtype=np.int64)
    patched[:len(rt.patched)] = rt.patched

    table = (
        pad(rt.kinds), pad(rt.out_start, fill=np.iinfo(np.int64).max),
        pad(rt.bit_start), pad(rt.widths), pad(rt.literals),
        pad(rt.delta_bases), pad(rt.patch_offset), jnp.asarray(patched),
        jnp.asarray(n_runs),
    )
    stream = jnp.asarray(np.frombuffer(data, dtype=np.uint8))
    return _expand_runs(stream, table, cap, signed)


# ---------------------------------------------------------------------------
# Boolean / byte RLE (present streams, RLEv1-style byte runs)
# ---------------------------------------------------------------------------

def decode_byte_rle(data: bytes, n: int) -> np.ndarray:
    """ORC byte-RLE (reference stream/ByteInputStream.java): header
    0..127 = run of (header+3) copies of next byte; 129..255 = 256-header
    literal bytes follow."""
    out = np.empty(n, dtype=np.uint8)
    pos = 0
    filled = 0
    while filled < n and pos < len(data):
        h = data[pos]
        pos += 1
        if h < 128:
            count = h + 3
            out[filled:filled + count] = data[pos]
            pos += 1
        else:
            count = 256 - h
            out[filled:filled + count] = np.frombuffer(
                data[pos:pos + count], dtype=np.uint8)
            pos += count
        filled += count
    return out[:n]


def decode_present(data: bytes, n_rows: int,
                   capacity: Optional[int] = None) -> np.ndarray:
    """Present stream -> bool[n_rows] validity (bit-packed big-endian over
    byte-RLE; reference stream/BooleanInputStream.java)."""
    n_bytes = (n_rows + 7) // 8
    packed = decode_byte_rle(data, n_bytes)
    bits = np.unpackbits(packed)[:n_rows]
    return bits.astype(bool)

"""ORC container metadata: postscript, footer, stripe footers.

From-scratch port of concept from the reference's ORC metadata layer
(reference presto-orc/.../metadata/OrcMetadataReader.java,
PostScript.java, Footer.java, StripeInformation.java, Stream.java,
ColumnEncoding.java; the message/field numbers are the public ORC spec's
orc_proto.proto). Host-side only — metadata is tiny.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, List, Optional, Tuple

from .proto import first, packed_varints, parse_message, read_varint

MAGIC = b"ORC"

COMPRESSION = {0: "none", 1: "zlib", 2: "snappy", 3: "lzo", 4: "lz4",
               5: "zstd"}

TYPE_KINDS = {
    0: "boolean", 1: "byte", 2: "short", 3: "int", 4: "long", 5: "float",
    6: "double", 7: "string", 8: "binary", 9: "timestamp", 10: "list",
    11: "map", 12: "struct", 13: "union", 14: "decimal", 15: "date",
    16: "varchar", 17: "char",
}

STREAM_KINDS = {0: "present", 1: "data", 2: "length", 3: "dictionary_data",
                4: "dictionary_count", 5: "secondary", 6: "row_index",
                7: "bloom_filter"}

ENCODINGS = {0: "direct", 1: "dictionary", 2: "direct_v2",
             3: "dictionary_v2"}


@dataclasses.dataclass
class OrcType:
    kind: str
    subtypes: List[int]
    field_names: List[str]
    max_length: Optional[int] = None
    precision: Optional[int] = None
    scale: Optional[int] = None


@dataclasses.dataclass
class StripeInfo:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    num_rows: int


@dataclasses.dataclass
class ColumnIntStats:
    min: Optional[int]
    max: Optional[int]
    has_null: bool


@dataclasses.dataclass
class StreamInfo:
    kind: str
    column: int
    length: int
    offset: int = 0        # filled while laying out the stripe


@dataclasses.dataclass
class StripeFooter:
    streams: List[StreamInfo]
    encodings: List[str]           # per column id
    dictionary_sizes: List[int]


@dataclasses.dataclass
class OrcFileTail:
    compression: str
    compression_block_size: int
    types: List[OrcType]
    stripes: List[StripeInfo]
    num_rows: int
    row_index_stride: int
    int_stats: Dict[int, ColumnIntStats]     # column id -> file stats
    # per-stripe column stats from the metadata section (may be empty)
    stripe_stats: List[Dict[int, ColumnIntStats]] = dataclasses.field(
        default_factory=list)


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def decompress_stream(data: bytes, compression: str) -> bytes:
    """Undo ORC's chunked compression framing: 3-byte LE header =
    (chunk_len << 1) | is_original, then chunk_len bytes (reference
    presto-orc/.../stream/CompressedOrcChunkLoader.java)."""
    if compression == "none":
        return data
    out = bytearray()
    pos = 0
    n = len(data)
    while pos + 3 <= n:
        header = int.from_bytes(data[pos:pos + 3], "little")
        pos += 3
        original = header & 1
        length = header >> 1
        chunk = data[pos:pos + length]
        pos += length
        if original:
            out += chunk
        elif compression == "zlib":
            out += zlib.decompress(chunk, wbits=-15)
        else:
            raise NotImplementedError(
                f"ORC compression {compression!r} is not supported "
                "(none/zlib are)")
    return bytes(out)


def _parse_type(buf: bytes) -> OrcType:
    f = parse_message(buf)
    subtypes: List[int] = []
    for v in f.get(2, []):
        if isinstance(v, bytes):
            subtypes.extend(packed_varints(v))
        else:
            subtypes.append(v)
    return OrcType(
        kind=TYPE_KINDS[first(f, 1, 0)],
        subtypes=subtypes,
        field_names=[b.decode() for b in f.get(3, [])],
        max_length=first(f, 4),
        precision=first(f, 5),
        scale=first(f, 6),
    )


def _parse_int_stats(buf: bytes) -> Optional[ColumnIntStats]:
    f = parse_message(buf)
    has_null = bool(first(f, 10, 0))
    raw = first(f, 2)
    if raw is None:
        return ColumnIntStats(None, None, has_null)
    g = parse_message(raw)
    mn, mx = first(g, 1), first(g, 2)
    # IntegerStatistics min/max are sint64 (zigzag)
    return ColumnIntStats(
        _zigzag(mn) if mn is not None else None,
        _zigzag(mx) if mx is not None else None,
        has_null,
    )


def tail_size_needed(suffix: bytes) -> int:
    """Bytes from end-of-file the full tail spans (postscript + footer +
    metadata). Callers re-read with a bigger suffix if this exceeds what
    they fetched."""
    ps_len = suffix[-1]
    ps = parse_message(suffix[-1 - ps_len:-1])
    return 1 + ps_len + first(ps, 1, 0) + first(ps, 5, 0)


def read_tail(data: bytes) -> OrcFileTail:
    """Parse the file tail. ``data`` may be the whole file or any suffix
    that covers postscript + footer + metadata (tail_size_needed)."""
    if len(data) < 4:
        raise ValueError("not an ORC file (too short)")
    ps_len = data[-1]
    ps = parse_message(data[-1 - ps_len:-1])
    footer_len = first(ps, 1, 0)
    compression = COMPRESSION[first(ps, 2, 0)]
    block_size = first(ps, 3, 256 * 1024)
    metadata_len = first(ps, 5, 0)
    magic = first(ps, 8000, b"")
    if magic != MAGIC:
        raise ValueError("bad postscript magic (not an ORC file?)")
    footer_raw = data[-1 - ps_len - footer_len:-1 - ps_len]
    footer = parse_message(decompress_stream(footer_raw, compression))
    stripe_stats: List[Dict[int, ColumnIntStats]] = []
    if metadata_len:
        meta_raw = data[-1 - ps_len - footer_len - metadata_len:
                        -1 - ps_len - footer_len]
        meta = parse_message(decompress_stream(meta_raw, compression))
        for sb in meta.get(1, []):          # repeated StripeStatistics
            cols: Dict[int, ColumnIntStats] = {}
            for ci, cb in enumerate(parse_message(sb).get(1, [])):
                st = _parse_int_stats(cb)
                if st is not None:
                    cols[ci] = st
            stripe_stats.append(cols)
    types = [_parse_type(b) for b in footer.get(4, [])]
    stripes = []
    for b in footer.get(3, []):
        f = parse_message(b)
        stripes.append(StripeInfo(
            offset=first(f, 1, 0), index_length=first(f, 2, 0),
            data_length=first(f, 3, 0), footer_length=first(f, 4, 0),
            num_rows=first(f, 5, 0)))
    int_stats: Dict[int, ColumnIntStats] = {}
    for ci, b in enumerate(footer.get(7, [])):
        st = _parse_int_stats(b)
        if st is not None:
            int_stats[ci] = st
    return OrcFileTail(
        compression=compression,
        compression_block_size=block_size,
        types=types,
        stripes=stripes,
        num_rows=first(footer, 6, 0),
        row_index_stride=first(footer, 8, 0),
        int_stats=int_stats,
        stripe_stats=stripe_stats,
    )


def parse_stripe_footer(raw: bytes, compression: str) -> StripeFooter:
    """Parse a stripe footer; stream offsets come out RELATIVE to the
    stripe start (index region first, then data — stream-list order)."""
    f = parse_message(decompress_stream(raw, compression))
    streams: List[StreamInfo] = []
    offset = 0
    for b in f.get(1, []):
        g = parse_message(b)
        s = StreamInfo(
            kind=STREAM_KINDS.get(first(g, 1, 0), "?"),
            column=first(g, 2, 0),
            length=first(g, 3, 0),
            offset=offset)
        offset += s.length
        streams.append(s)
    encodings: List[str] = []
    dict_sizes: List[int] = []
    for b in f.get(2, []):
        g = parse_message(b)
        encodings.append(ENCODINGS[first(g, 1, 0)])
        dict_sizes.append(first(g, 2, 0))
    return StripeFooter(streams=streams, encodings=encodings,
                        dictionary_sizes=dict_sizes)

"""Verifier: replay queries against a control and a test runner, compare.

The role of presto-verifier (reference
presto-verifier/.../verifier/Verifier.java + Validator.java:68 — run
each query on a control and a test cluster, normalize, diff, report
MATCH / MISMATCH / failures). Runners are anything with
``execute(sql) -> QueryResult`` (LocalRunner, DistributedRunner,
ClusterRunner, StatementClient wrapper), so the same harness validates
local-vs-SPMD, local-vs-cluster, or version-vs-version.
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence


@dataclasses.dataclass
class VerifyResult:
    query: str
    status: str          # MATCH | MISMATCH | CONTROL_FAILED | TEST_FAILED
    detail: str = ""
    control_ms: float = 0.0
    test_ms: float = 0.0


def _normalize(rows: Sequence, precision: int) -> List:
    out = []
    for r in rows:
        vals = []
        for v in r:
            if hasattr(v, "item"):
                v = v.item()
            if isinstance(v, float):
                v = round(v, precision)
            vals.append(v)
        out.append(tuple(vals))
    # order-insensitive: the reference re-sorts deterministically too
    # (Validator resultsMatch over sorted lists)
    return sorted(out, key=repr)


class Verifier:
    def __init__(self, control, test, precision: int = 6):
        self.control = control
        self.test = test
        self.precision = precision

    def verify_one(self, sql: str) -> VerifyResult:
        t0 = time.perf_counter()
        try:
            want = self.control.execute(sql)
        except Exception as e:
            return VerifyResult(sql, "CONTROL_FAILED",
                                f"{type(e).__name__}: {e}")
        control_ms = (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        try:
            got = self.test.execute(sql)
        except Exception as e:
            return VerifyResult(sql, "TEST_FAILED",
                                f"{type(e).__name__}: {e}",
                                control_ms=control_ms)
        test_ms = (time.perf_counter() - t1) * 1e3
        w = _normalize(want.rows, self.precision)
        g = _normalize(got.rows, self.precision)
        if len(w) != len(g):
            return VerifyResult(
                sql, "MISMATCH",
                f"row count: control={len(w)} test={len(g)}",
                control_ms, test_ms)
        for i, (a, b) in enumerate(zip(w, g)):
            if a != b:
                return VerifyResult(
                    sql, "MISMATCH",
                    f"first differing row {i}: control={a!r} test={b!r}",
                    control_ms, test_ms)
        return VerifyResult(sql, "MATCH", "", control_ms, test_ms)

    def run(self, queries: Sequence[str]) -> List[VerifyResult]:
        return [self.verify_one(q) for q in queries]


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: verify a ;-separated query file local-control vs
    distributed-test (the in-repo analogue of the reference's
    verifier CLI)."""
    import argparse

    from .exec.runner import LocalRunner

    p = argparse.ArgumentParser(description="presto_tpu verifier")
    p.add_argument("queries", help="file of ;-separated SQL statements")
    p.add_argument("--tpch-sf", type=float, default=0.01)
    p.add_argument("--test", choices=["distributed", "local"],
                   default="distributed")
    args = p.parse_args(argv)
    with open(args.queries, encoding="utf-8") as f:
        queries = [q.strip() for q in f.read().split(";") if q.strip()]
    control = LocalRunner(tpch_sf=args.tpch_sf)
    if args.test == "distributed":
        from .exec.distributed import DistributedRunner
        test = DistributedRunner(catalogs=control.session.catalogs)
    else:
        test = LocalRunner(tpch_sf=args.tpch_sf)
    results = Verifier(control, test).run(queries)
    for r in results:
        print(f"{r.status:15s} {r.control_ms:8.1f}ms {r.test_ms:8.1f}ms  "
              f"{r.query[:80]!r}" + (f"  -- {r.detail}" if r.detail
                                     else ""))
    failed = sum(r.status != "MATCH" for r in results)
    print(f"{len(results) - failed}/{len(results)} MATCH")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Discovery: dynamic worker membership by announcement.

The role of the reference's embedded discovery service + node manager
(reference metadata/DiscoveryNodeManager.java:68 tracking active workers
from announcements; server/EmbeddedDiscoveryConfig.java; workers
announce over airlift discovery and may join at any time = elastic
scale-out). Workers POST /v1/announce to the coordinator on a heartbeat
cadence; entries expire after a TTL so vanished workers drop out of
scheduling without explicit deregistration.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Dict, List, Tuple


class DiscoveryNodeManager:
    """Coordinator-side registry of announced workers."""

    def __init__(self, ttl_s: float = 15.0):
        self.ttl_s = ttl_s
        self._nodes: Dict[str, Tuple[str, float]] = {}
        self._lock = threading.Lock()

    def announce(self, node_id: str, url: str) -> None:
        with self._lock:
            self._nodes[node_id] = (url, time.monotonic())

    def active_urls(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return sorted(url for url, seen in self._nodes.values()
                          if now - seen <= self.ttl_s)

    def nodes(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [{"nodeId": nid, "uri": url,
                     "age_s": round(now - seen, 3),
                     "active": now - seen <= self.ttl_s}
                    for nid, (url, seen) in sorted(self._nodes.items())]


class Announcer:
    """Worker-side announce loop (the airlift Announcer role)."""

    def __init__(self, discovery_uri: str, node_id: str, self_url: str,
                 interval_s: float = 5.0):
        self.discovery_uri = discovery_uri.rstrip("/")
        self.node_id = node_id
        self.self_url = self_url
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def announce_once(self) -> bool:
        body = json.dumps({"nodeId": self.node_id,
                           "uri": self.self_url}).encode()
        req = urllib.request.Request(
            f"{self.discovery_uri}/v1/announce", data=body,
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                return True
        except Exception:
            return False

    def start(self) -> None:
        self.announce_once()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.announce_once()

"""Discovery: dynamic worker membership by announcement.

The role of the reference's embedded discovery service + node manager
(reference metadata/DiscoveryNodeManager.java:68 tracking active workers
from announcements; server/EmbeddedDiscoveryConfig.java; workers
announce over airlift discovery and may join at any time = elastic
scale-out). Workers POST /v1/announce to the coordinator on a heartbeat
cadence; entries expire after a TTL so vanished workers drop out of
scheduling without explicit deregistration.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Dict, List, Tuple


class DiscoveryNodeManager:
    """Coordinator-side registry of announced workers. Announcements
    carry the node's lifecycle state: a draining worker (graceful
    shutdown) re-announces as ``SHUTTING_DOWN`` so the scheduler stops
    assigning new tasks to it without waiting for the next ``/v1/info``
    heartbeat sweep."""

    def __init__(self, ttl_s: float = 15.0):
        self.ttl_s = ttl_s
        self._nodes: Dict[str, Tuple[str, float, str, str]] = {}
        self._lock = threading.Lock()

    def announce(self, node_id: str, url: str,
                 state: str = "ACTIVE", role: str = "worker") -> None:
        """Join/refresh membership — any time, mid-query included (the
        scheduler's next sweep sees the node and re-created tasks land
        on it). State ``GONE`` is an explicit leave: the node drops
        out immediately instead of waiting out the TTL. ``role``
        separates the planes sharing this registry: ``worker`` nodes
        enter task scheduling; ``coordinator`` peers (the serving
        fleet) are membership-only."""
        if state == "GONE":
            self.remove(node_id)
            return
        with self._lock:
            self._nodes[node_id] = (url, time.monotonic(),
                                    state or "ACTIVE",
                                    role or "worker")

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def active_urls(self) -> List[str]:
        """Fresh WORKER announcements, draining nodes included — they
        still serve their running tasks' buffers; ``states()`` is the
        scheduler's don't-assign filter. Coordinator-role peers never
        appear here: the scheduler must not ship tasks to a fleet
        frontend."""
        now = time.monotonic()
        with self._lock:
            return sorted(url
                          for url, seen, _, role in self._nodes.values()
                          if role == "worker"
                          and now - seen <= self.ttl_s)

    def peer_urls(self, self_url: str = "") -> List[str]:
        """Fresh coordinator-role peers (the serving fleet), excluding
        ``self_url`` — the fleet member's broadcast fan-out set when
        peers are discovered rather than configured."""
        now = time.monotonic()
        me = self_url.rstrip("/")
        with self._lock:
            return sorted(url
                          for url, seen, _, role in self._nodes.values()
                          if role == "coordinator"
                          and now - seen <= self.ttl_s
                          and url.rstrip("/") != me)

    def states(self) -> Dict[str, str]:
        """url -> last announced lifecycle state (workers only — the
        consumer is the scheduler's don't-assign filter)."""
        with self._lock:
            return {url: state
                    for url, _, state, role in self._nodes.values()
                    if role == "worker"}

    def nodes(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [{"nodeId": nid, "uri": url,
                     "age_s": round(now - seen, 3),
                     "state": state, "role": role,
                     "active": now - seen <= self.ttl_s}
                    for nid, (url, seen, state, role)
                    in sorted(self._nodes.items())]


class Announcer:
    """Worker-side announce loop (the airlift Announcer role).

    ``discovery_uri`` may be a single coordinator URI or a list: a
    worker in a multi-coordinator fleet announces to EVERY coordinator
    each beat, so all fleet members schedule over the same pool without
    any cross-coordinator membership relay."""

    def __init__(self, discovery_uri, node_id: str, self_url: str,
                 interval_s: float = 5.0, role: str = "worker"):
        uris = ([discovery_uri] if isinstance(discovery_uri, str)
                else list(discovery_uri))
        self.discovery_uris = [u.rstrip("/") for u in uris]
        # single-URI callers keep reading .discovery_uri
        self.discovery_uri = self.discovery_uris[0]
        self.node_id = node_id
        self.self_url = self_url
        self.interval_s = interval_s
        self.role = role
        self.state = "ACTIVE"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def set_state(self, state: str) -> None:
        """Change the announced lifecycle state and push it out
        immediately (a draining worker must not wait one announce
        interval before the scheduler stops feeding it)."""
        self.state = state
        self.announce_once()

    def announce_once(self) -> bool:
        body = json.dumps({"nodeId": self.node_id,
                           "uri": self.self_url,
                           "state": self.state,
                           "role": self.role}).encode()
        ok = False
        for uri in self.discovery_uris:
            req = urllib.request.Request(
                f"{uri}/v1/announce", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=5):
                    ok = True
            except Exception:
                # one dead coordinator must not stop the others from
                # hearing about this worker
                continue
        return ok

    def start(self) -> None:
        self.announce_once()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def deregister(self) -> None:
        """Explicit leave: stop the loop and push one final ``GONE``
        announcement so the coordinator drops this node now (elastic
        scale-in), not after the TTL."""
        self._stop.set()
        self.state = "GONE"
        self.announce_once()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.announce_once()

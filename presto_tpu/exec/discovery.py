"""Discovery: dynamic worker membership by announcement.

The role of the reference's embedded discovery service + node manager
(reference metadata/DiscoveryNodeManager.java:68 tracking active workers
from announcements; server/EmbeddedDiscoveryConfig.java; workers
announce over airlift discovery and may join at any time = elastic
scale-out). Workers POST /v1/announce to the coordinator on a heartbeat
cadence; entries expire after a TTL so vanished workers drop out of
scheduling without explicit deregistration.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Dict, List, Tuple


class DiscoveryNodeManager:
    """Coordinator-side registry of announced workers. Announcements
    carry the node's lifecycle state: a draining worker (graceful
    shutdown) re-announces as ``SHUTTING_DOWN`` so the scheduler stops
    assigning new tasks to it without waiting for the next ``/v1/info``
    heartbeat sweep."""

    def __init__(self, ttl_s: float = 15.0):
        self.ttl_s = ttl_s
        self._nodes: Dict[str, Tuple[str, float, str]] = {}
        self._lock = threading.Lock()

    def announce(self, node_id: str, url: str,
                 state: str = "ACTIVE") -> None:
        """Join/refresh membership — any time, mid-query included (the
        scheduler's next sweep sees the node and re-created tasks land
        on it). State ``GONE`` is an explicit leave: the node drops
        out immediately instead of waiting out the TTL."""
        if state == "GONE":
            self.remove(node_id)
            return
        with self._lock:
            self._nodes[node_id] = (url, time.monotonic(),
                                    state or "ACTIVE")

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def active_urls(self) -> List[str]:
        """Fresh announcements, draining nodes included — they still
        serve their running tasks' buffers; ``states()`` is the
        scheduler's don't-assign filter."""
        now = time.monotonic()
        with self._lock:
            return sorted(url for url, seen, _ in self._nodes.values()
                          if now - seen <= self.ttl_s)

    def states(self) -> Dict[str, str]:
        """url -> last announced lifecycle state."""
        with self._lock:
            return {url: state
                    for url, _, state in self._nodes.values()}

    def nodes(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [{"nodeId": nid, "uri": url,
                     "age_s": round(now - seen, 3),
                     "state": state,
                     "active": now - seen <= self.ttl_s}
                    for nid, (url, seen, state)
                    in sorted(self._nodes.items())]


class Announcer:
    """Worker-side announce loop (the airlift Announcer role)."""

    def __init__(self, discovery_uri: str, node_id: str, self_url: str,
                 interval_s: float = 5.0):
        self.discovery_uri = discovery_uri.rstrip("/")
        self.node_id = node_id
        self.self_url = self_url
        self.interval_s = interval_s
        self.state = "ACTIVE"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def set_state(self, state: str) -> None:
        """Change the announced lifecycle state and push it out
        immediately (a draining worker must not wait one announce
        interval before the scheduler stops feeding it)."""
        self.state = state
        self.announce_once()

    def announce_once(self) -> bool:
        body = json.dumps({"nodeId": self.node_id,
                           "uri": self.self_url,
                           "state": self.state}).encode()
        req = urllib.request.Request(
            f"{self.discovery_uri}/v1/announce", data=body,
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                return True
        except Exception:
            return False

    def start(self) -> None:
        self.announce_once()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def deregister(self) -> None:
        """Explicit leave: stop the loop and push one final ``GONE``
        announcement so the coordinator drops this node now (elastic
        scale-in), not after the TTL."""
        self._stop.set()
        self.state = "GONE"
        self.announce_once()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.announce_once()

"""Local plan executor: logical plan -> streaming batch iterators.

Conceptual parity with the reference's LocalExecutionPlanner + Driver
pipelines (reference presto-main/.../sql/planner/LocalExecutionPlanner.java:357
visitTableScan/visitAggregation/visitJoin and operator/Driver.java): each
plan node becomes a generator over device batches, so scan->filter->project
->partial-agg chains stream without materializing, join build sides and
sorts drain their input exactly like HashBuilderOperator / OrderByOperator,
and expression compilation happens once per (expr, schema) via the kernel
compiler's cache.

Init plans (uncorrelated scalar subqueries) run before the main plan and
their scalar results substitute into expressions — the reference's
ExchangeClient-fed init semantics without a network hop.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity, concat_batches
from ..expr import ir
from ..expr.compiler import compile_filter, compile_projection
from ..expr.rewrite import rewrite as ir_rewrite
from ..ops.aggregation import AggSpec
from ..ops.jitcache import global_aggregate_jit as global_aggregate, grouped_aggregate_jit as grouped_aggregate
from ..ops.jitcache import (
    build_key_ranks_jit, build_match_mask_jit, expand_join_jit,
    key_bounds_violation_jit, lookup_join_jit, lookup_join_pallas_jit,
    match_count_max_jit, prepare_build_jit, prepare_direct_jit,
    prepare_direct_keyed_jit, semi_join_mask_jit,
)
from ..obs.metrics import REGISTRY
from ..obs.trace import TRACER

#: grouped-aggregation kernel dispatch, per operator (first batch decides
#: and the plan is shape-stable): dense composite-code path (broadcast or
#: scatter — no sort) vs the sort-segment path. The trace-level signal
#: the stats-bounded grouping tests assert on.
_AGG_DENSE_SELECTED = REGISTRY.counter("agg_dense_path_selected_total")
_AGG_SORT_SELECTED = REGISTRY.counter("agg_sort_path_selected_total")

#: fused-chain lane accounting: capacities entering the chain (source)
#: vs entering the tail's payload gathers (post mask + compaction). The
#: ratio IS the gather-lane reduction the selectivity-first head buys —
#: the observable the q27-shaped star-chain tests assert on.
_FUSED_SOURCE_LANES = REGISTRY.counter("fused_source_lanes_total")
_FUSED_TAIL_LANES = REGISTRY.counter("fused_tail_lanes_total")


def _note_join_strategy(stats, node, strategy: str, dist: str) -> None:
    """Join-dispatch observability: one count per executed join/semi
    operator, labeled strategy (direct / sorted / expand) x
    distribution — the trace-level signal the strategy-selection tests
    assert on, next to EXPLAIN ANALYZE's per-row [strategy ...] suffix."""
    REGISTRY.counter(
        f"join_strategy_selected_total.{strategy}.{dist}").inc()
    if stats is not None and hasattr(stats, "record_join_strategy"):
        stats.record_join_strategy(node, strategy, dist)
from ..ops.join import expand_join, semi_join_mask
from ..ops.sort import SortKey, limit as limit_kernel, sort_batch, top_n
from ..planner.plan import (
    AggregationNode, DistinctNode, FilterNode, GroupIdNode, JoinNode,
    LimitNode, OutputNode, PlanNode, ProjectNode, SemiJoinNode, SortNode,
    TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from ..planner.planner import InitPlanRef, LogicalPlan, Session


from ..planner.planner import bool_property  # noqa: F401 (re-export)


@dataclasses.dataclass
class QueryResult:
    names: List[str]
    types: List[T.Type]
    rows: List[tuple]


def _default_grouping_batch(node: AggregationNode) -> Batch:
    """One default row per empty grouping set for empty-input
    aggregations (reference AggregationNode.hasDefaultOutput +
    AggregationOperator's default output page): keys NULL, $group_id set,
    count-family aggregates 0, everything else NULL."""
    nk = len(node.group_indices)
    data: Dict[str, tuple] = {}
    n = len(node.default_gids)
    for pos, f in enumerate(node.fields):
        if pos < nk - 1:
            vals = [None] * n
        elif pos == nk - 1:                 # the $group_id column
            vals = [int(g) for g in node.default_gids]
        else:
            agg = node.aggs[pos - nk]
            zero = agg.fn in ("count", "count_star", "approx_distinct")
            vals = [0 if zero else None] * n
        data[f.name] = (f.type, vals)
    return Batch.from_pydict(data)


def run_init_plans(ex, plan: LogicalPlan) -> None:
    """Run uncorrelated scalar subqueries (init plans), exposing results to
    the main plan AND to later init plans: inner subqueries are appended
    first (lower indices), so binding the live list to the executor before
    the loop makes a nested init plan's InitPlanRef resolvable while the
    outer one runs."""
    ex.mark_shared(list(plan.init_plans) + [plan.root])
    ex.init_values = init_values = []
    for p in plan.init_plans:
        rows = [r for b in ex.run(p) for r in b.to_pylist()]
        if len(rows) > 1:
            raise ValueError("scalar subquery returned more than one row")
        init_values.append(rows[0][0] if rows else None)


def execute_plan(plan: LogicalPlan, session: Session,
                 rows_per_batch: int = 1 << 17, stats=None,
                 collect_rows: bool = True, cancel_event=None,
                 split_restrict=None) -> QueryResult:
    import time as _time

    from ..expr import params as P
    from ..obs import flight as _flight
    from ..obs.profiler import profiled
    from ..obs.trace import current_span_ids
    from .taskexec import GLOBAL as scheduler
    # mesh-native execution (the default with >1 device): the SPMD
    # executor shards this plan over the device mesh whenever the
    # auto-router (exec/distributed.select_mesh) accepts it —
    # mesh_execution=off pins the single-device path. Split-restricted
    # runs (result-cache incremental delta) stay single-device: the
    # restriction applies at the local scan node.
    from .distributed import (
        DistributedExecutor, mesh_flight_on, select_mesh,
    )
    bindings = getattr(session, "param_bindings", None)
    mesh = select_mesh(session, plan) if split_restrict is None else None
    if mesh is not None and bindings:
        # SPMD shard programs trace expressions inside their own jits
        # where a Param has no operand channel — materialize this
        # query's bindings into literals (correctness over executable
        # sharing; the cached template itself is never mutated)
        plan = P.bind_plan(plan, bindings)
        bindings = None
    if mesh is not None:
        ex = DistributedExecutor(session, rows_per_batch, mesh,
                                 stats=stats)
        n_chips = int(mesh.devices.size)
    else:
        ex = _Executor(session, rows_per_batch, stats=stats)
        n_chips = 1
    ex.cancel_event = cancel_event
    ex.split_restrict = split_restrict
    # admitted queries register under their resource group's scheduler
    # share (serving/groups.py): quanta are allotted per group by
    # schedulingWeight, then per task within the group — and billed
    # per chip, so a mesh query pays for every device it occupies
    serving = getattr(session, "serving", None)
    handle = (scheduler.task(
        name=str(id(ex)),
        group=serving.scheduler_group if serving is not None else "",
        weight=serving.weight if serving is not None else 1,
        label=serving.group_path if serving is not None else None,
        devices=n_chips)
        if bool_property(session, "fair_scheduling", True) else None)
    # device-time profiling: per-dispatch block_until_ready bracketing +
    # per-operator attribution (obs/profiler.py). On under the `profile`
    # session property, and always under EXPLAIN ANALYZE — analyze mode
    # already pays a per-batch sync for live row counts, so device truth
    # rides along; plain queries pay one contextvar load per dispatch.
    profile_on = (bool_property(session, "profile", False)
                  or (stats is not None
                      and getattr(stats, "count_rows", False)))
    # mesh flight recorder (obs/flight.py): every mesh-path execution
    # records its exchange rounds for the post-query wall-clock
    # attribution, unless mesh_flight=off
    flight = None
    fl_token = None
    if mesh is not None and mesh_flight_on(session):
        qid = (str(current_span_ids().get("query_id") or "")
               or f"mesh_{_flight.next_seq():06d}")
        flight = _flight.FlightRecorder(qid, int(mesh.devices.size))
        fl_token = _flight.CURRENT_FLIGHT.set(flight)
    t_flight0 = _time.perf_counter()
    try:
        # template bindings: ir.Param kernels fetch this query's
        # literal values from the scope (exchange driver threads copy
        # their spawn context, so the scope survives the q3-style
        # background pipelines)
        with P.bound(bindings), profiled(profile_on):
            run_init_plans(ex, plan)
            root = plan.root
            rows: List[tuple] = []
            out_batches: List[Batch] = []
            # one fair-scheduler quantum per produced output batch:
            # concurrent queries interleave at batch granularity by
            # cumulative device time (the reference's TaskExecutor
            # 1s-quantum role)
            it = ex.run(root.child)
            sentinel = object()
            try:
                while True:
                    # cancellation interrupts between quanta, like the
                    # reference Driver checking its DriverYieldSignal/state
                    # between page moves (operator/Driver.java:262;
                    # DispatchManager.java:134)
                    ex._check_cancel()
                    b = scheduler.run_quantum(handle,
                                              lambda: next(it, sentinel))
                    if b is sentinel:
                        break
                    if collect_rows:
                        out_batches.append(b)
            finally:
                # closing the generator runs suspended finally blocks (the
                # threaded scan's stop.set()) so cancel/error doesn't leave
                # prefetch workers spinning
                it.close()
            ex.check_errors()
            if collect_rows:
                if flight is not None:
                    with flight.timed("drain"):
                        rows = [r for b in out_batches
                                for r in b.to_pylist()]
                else:
                    rows = [r for b in out_batches
                            for r in b.to_pylist()]
            return QueryResult(names=[f.name for f in root.fields],
                               types=[f.type for f in root.fields],
                               rows=rows)
    finally:
        if flight is not None:
            _flight.CURRENT_FLIGHT.reset(fl_token)
            flight.finish(_time.perf_counter() - t_flight0)
            if stats is not None:
                stats.mesh_flight = flight
        if handle is not None:
            handle.close()


def _plan_schema(node: PlanNode) -> Schema:
    return Schema([(f.name, f.type) for f in node.fields])


_DYN_TYPES = (T.BigintType, T.IntegerType, T.SmallintType, T.TinyintType,
              T.DateType)


def _apply_dynamic_bounds(probe: Batch,
                          dyn: List[Tuple[int, int, int]]) -> Batch:
    """Device-side probe prefilter: drop rows whose key cannot match any
    build row (outside [lo, hi] or NULL — inner-join semantics). Shrinks
    the join kernel's input; the scan-level pushdown handles IO."""
    keep = probe.row_mask
    for pk, lo, hi in dyn:
        c = probe.columns[pk]
        keep = keep & c.validity & (c.data >= lo) & (c.data <= hi)
    return Batch(probe.schema, probe.columns, keep)


def mark_exists_mask(probe: Batch, build: Batch, probe_keys, build_keys,
                     residual, negated: bool, max_matches: int, ex=None):
    """Correlated-EXISTS mark: probe row passes iff ANY build row with
    equal keys satisfies the residual predicate (over probe fields +
    build fields). The decorrelated mark-join shape of reference
    TransformExistsApplyToCorrelatedJoin.java: expand the m:n matches,
    filter by the residual, then test membership of each probe row id in
    the surviving matches."""
    from ..expr.rewrite import referenced_inputs, remap_inputs
    cap = probe.capacity
    rid = Column(T.BIGINT, jnp.arange(cap, dtype=jnp.int64),
                 probe.row_mask, None)
    schema2 = Schema(list(zip(probe.schema.names, probe.schema.types))
                     + [("$rid", T.BIGINT)])
    probe2 = Batch(schema2, list(probe.columns) + [rid], probe.row_mask)
    payload = list(range(len(build.columns)))
    pnames = [f"$f{i}" for i in payload]
    expanded = expand_join(probe2, build, probe_keys, build_keys,
                           payload, pnames, "inner", max_matches)
    # expanded layout: probe cols, $rid, build cols — shift build refs by 1
    n_src = len(probe.columns)
    shift = {i: (i if i < n_src else i + 1)
             for i in referenced_inputs(residual)}
    filt = compile_filter(remap_inputs(residual, shift), expanded.schema,
                          errors=True)
    kept, err = filt(expanded)
    if err is not None and ex is not None:
        ex.error_flags.append(err)
    return semi_join_mask(probe2, kept, [n_src], [n_src],
                          negated=negated, null_aware=False)


import functools


@functools.lru_cache(maxsize=None)
def unnest_expand_fn(exprs, ordinality: bool, schema: Schema):
    """Compiled lateral array expansion: [cap, L] element tiles flatten to
    [cap*L] rows, outer columns repeat per element slot (reference
    operator/unnest/UnnestOperator.java). Rows beyond an array's length
    are masked dead; multiple arrays zip to the longest (shorter ones
    padded with NULL elements)."""
    import jax

    from ..expr.compiler import eval_expr
    from ..expr.functions import Val

    def expand(b: Batch) -> Batch:
        inputs = [Val(c.data, c.validity, c.type, c.dictionary)
                  for c in b.columns]
        if not inputs:
            inputs = [Val(b.row_mask, b.row_mask, T.BOOLEAN)]
        arrs = [eval_expr(e, inputs) for e in exprs]
        # row-level errors raised inside the array expressions (e.g.
        # UNNEST(transform(a, x -> 1/x))) must fail the query, matching
        # compile_projection(errors=True)
        from ..expr.compiler import _err_scalar
        err_scalar = _err_scalar([a.err for a in arrs], b.row_mask)
        widths = [a.data[0].shape[1] for a in arrs]
        L = max(widths)
        cap = b.capacity
        # effective length: NULL array -> 0 rows (cross-join semantics)
        eff_lens = [jnp.where(a.valid, a.data[1], 0) for a in arrs]
        max_len = eff_lens[0]
        for ln in eff_lens[1:]:
            max_len = jnp.maximum(max_len, ln)
        slot = jnp.broadcast_to(jnp.arange(L)[None, :], (cap, L))
        out_mask = (b.row_mask[:, None] & (slot < max_len[:, None])
                    ).reshape(-1)
        cols = []
        for c in b.columns:
            data = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, L, axis=0), c.data)
            cols.append(Column(c.type, data,
                               jnp.repeat(c.validity, L, axis=0),
                               c.dictionary))
        for a, w, ln in zip(arrs, widths, eff_lens):
            values, _, elem_valid = a.data
            if w < L:
                values = jnp.pad(values, ((0, 0), (0, L - w)))
                elem_valid = jnp.pad(elem_valid, ((0, 0), (0, L - w)))
            ev = elem_valid & (slot < ln[:, None])
            cols.append(Column(a.type.element, values.reshape(-1),
                               ev.reshape(-1), a.dictionary))
        if ordinality:
            cols.append(Column(T.BIGINT,
                               (slot + 1).astype(jnp.int64).reshape(-1),
                               out_mask, None))
        return Batch(schema, cols, out_mask), err_scalar

    # registered jit entry (not a raw @jax.jit): compile time,
    # invocations and profiled device time land in obs.profiler's
    # EXECUTABLES like every jitcache kernel, and the trace-safety lint
    # (tools/analyze/tracing.py) holds the line on new bypasses
    from ..ops.jitcache import _TimedEntry
    return _TimedEntry("unnest_expand", jax.jit(expand),
                       (exprs, ordinality))


class _Executor:
    def __init__(self, session: Session, rows_per_batch: int,
                 stats=None):
        self.session = session
        self.rows_per_batch = rows_per_batch
        self.init_values: List[object] = []
        self.stats = stats
        # set by execute_plan: a threading.Event checked per scan batch
        # so a DELETE-cancel interrupts a query mid-drain
        self.cancel_event = None
        # result-cache incremental delta: {(catalog, table): predicate
        # over Split} restricting a scan to the changed splits only
        # (serving/resultcache.py); None = scan everything
        self.split_restrict = None
        # device int32 scalars from error-checking kernels; reduced to one
        # host sync by check_errors() after the plan drains
        self.error_flags: List = []
        self._shared: set = set()
        self._ever_shared: set = set()
        self._materialized: Dict[PlanNode, List[Batch]] = {}
        # runtime (dynamic-filter) scan bounds: scan node -> [(col, lo, hi)]
        self.dynamic_pushdown: Dict[PlanNode, List[Tuple]] = {}
        # grouped (lifespan) execution: scan node -> the split list it
        # is currently restricted to (one bucket's files; reference
        # execution/Lifespan.java:26 + scheduler/group/LifespanScheduler)
        self.lifespan_splits: Dict[PlanNode, List] = {}
        from ..memory import QueryMemoryPool

        def _int_prop(name, default=None):
            v = session.properties.get(name, default)
            return int(v) if v is not None else None
        self.pool = QueryMemoryPool(
            _int_prop("query_max_memory"),
            # second spill tier: staged host bytes beyond this flush to
            # compressed pages on disk (reference NodeSpillConfig)
            disk_threshold=_int_prop("spill_to_disk_bytes", 4 << 30),
            spill_dir=session.properties.get("spill_path"),
            # admitted queries mirror reservations to their resource
            # group's ledger (kill-or-queue on group memory limits)
            group=getattr(session, "serving", None))
        self.spill_partitions = int(
            session.properties.get("spill_partitions", 16))
        session.last_memory_stats = self.pool.stats

    def _check_cancel(self) -> None:
        ev = self.cancel_event
        if ev is not None and ev.is_set():
            from ..errors import QueryCancelledError
            raise QueryCancelledError()

    def checked_filter(self, pred: ir.Expr, schema: Schema):
        """Compiled filter that feeds row errors into this query's
        error_flags (for predicates applied outside Filter nodes, e.g.
        join ON residuals)."""
        fn = compile_filter(pred, schema, errors=True)

        def run(b: Batch) -> Batch:
            out, err = fn(b)
            if err is not None:
                self.error_flags.append(err)
            return out
        return run

    def check_errors(self) -> None:
        """Raise the highest-coded row error seen by any kernel this query
        (one host sync over all collected device scalars)."""
        if not self.error_flags:
            return
        import numpy as np

        from ..errors import QueryError
        with TRACER.span("device-sync", what="error-flags"):
            codes = np.asarray(jnp.stack(self.error_flags))
        self.error_flags = []
        code = int(codes.max())
        if code:
            raise QueryError(code)

    def mark_shared(self, roots: Sequence[PlanNode]) -> None:
        """Pre-scan for structurally repeated subplans (e.g. the shared
        input of a GROUPING SETS union): their output is materialized once
        and replayed — the executor-side equivalent of the reference's
        single-pass GroupIdOperator over a shared source."""
        from collections import Counter
        counts: Counter = Counter()

        def walk(n: PlanNode) -> None:
            counts[n] += 1
            if counts[n] > 1:
                return
            for c in n.children:
                walk(c)

        for r in roots:
            walk(r)
        self._shared = {n for n, c in counts.items() if c > 1}
        # never-discarded copy: dynamic-filter pushdown must see a
        # subtree as multi-consumer even after its memo was abandoned
        # under memory pressure (run() discards from _shared then)
        self._ever_shared = set(self._shared)

    # -- expression preparation ---------------------------------------------
    def _resolve(self, e: ir.Expr) -> ir.Expr:
        def fn(n: ir.Expr) -> ir.Expr:
            if isinstance(n, ir.Literal) and isinstance(n.value, InitPlanRef):
                return ir.Literal(type=n.type,
                                  value=self.init_values[n.value.index])
            return n
        return ir_rewrite(e, fn)

    # -- dispatch -------------------------------------------------------------
    def run(self, node: PlanNode) -> Iterator[Batch]:
        if node in self._materialized:
            # cache replay: the node's stats already recorded the one real
            # execution — don't re-wrap or double-count
            return iter(self._materialized[node])
        m = getattr(self, "_" + type(node).__name__)
        if node in self._shared:
            return self._run_memoized(node, m)
        it = m(node)
        if self.stats is not None:
            it = self.stats.wrap(node, it)
        if TRACER.enabled:
            # operator span: first batch to exhaustion, inclusive of
            # children (the printer/Chrome viewer nests them by time)
            it = TRACER.wrap_iter(
                "op:" + type(node).__name__.replace("Node", ""), it)
        return it

    def _run_memoized(self, node: PlanNode, m) -> Iterator[Batch]:
        """Materialize a shared subplan's output once, within the memory
        budget: each cached batch reserves from the query pool, and if the
        pool can't hold the next batch the cache is abandoned (repeat
        consumers re-execute instead of OOMing device memory)."""
        import itertools

        from .spill import batch_device_bytes
        ctx = self.pool.context(f"memo-{type(node).__name__}")
        it = m(node)
        if self.stats is not None:
            it = self.stats.wrap(node, it)
        if TRACER.enabled:
            it = TRACER.wrap_iter(
                "op:" + type(node).__name__.replace("Node", ""), it,
                memoized=True)
        out: List[Batch] = []
        for b in it:
            if not ctx.pool.try_reserve(batch_device_bytes(b), ctx):
                # over budget: abandon the cache; this consumer streams on
                # and later consumers re-execute the subplan
                ctx.release_all()
                self._shared.discard(node)
                return itertools.chain(out, [b], it)
            out.append(b)
        self._materialized[node] = out
        return iter(out)

    def _OutputNode(self, node: OutputNode) -> Iterator[Batch]:
        return self.run(node.child)

    # -- leaves ---------------------------------------------------------------
    def _scan_pushdown_fn(self, node: TableScanNode):
        """Closure yielding a scan's EFFECTIVE pushdown, re-evaluated
        per split: dynamic (join build) bounds may arrive while earlier
        splits are already streaming — later splits still benefit (the
        reference's dynamic filters race the probe scan the same way).
        Shared with the cluster worker's task executor."""
        def current_pushdown():
            pushdown = node.pushdown or None
            dyn = self.dynamic_pushdown.get(node)
            if dyn:
                # intersect per column: connectors keep one bound per
                # name, so appending would let a wider dynamic bound
                # shadow a tighter WHERE-derived one
                merged: Dict[str, List] = {}
                for name, lo, hi in list(pushdown or ()) + dyn:
                    b = merged.setdefault(name, [lo, hi])
                    if lo is not None:
                        b[0] = lo if b[0] is None else max(b[0], lo)
                    if hi is not None:
                        b[1] = hi if b[1] is None else min(b[1], hi)
                pushdown = tuple((n, lo, hi)
                                 for n, (lo, hi) in merged.items())
            return pushdown
        return current_pushdown

    def _TableScanNode(self, node: TableScanNode) -> Iterator[Batch]:
        """Split-parallel scan through the device scan cache + async
        prefetching pipeline (exec/scancache.py): hot split data replays
        from device memory across queries, cold splits decode/stage on
        background threads ahead of the consumer so device compute
        overlaps input production — the role of the reference's split
        pipeline (execution/SqlTaskExecution.java:390 one driver per
        split + BufferingSplitSource prefetch).

        Delivery is in deterministic split order (per-split reorder
        queues): physical row order feeds order-sensitive downstream
        semantics (ROWS window frames with ties, LIMIT-without-ORDER),
        so prefetch must not reshuffle it run to run."""
        from . import scancache

        conn = self.session.catalogs.get(node.catalog)
        current_pushdown = self._scan_pushdown_fn(node)
        opts = scancache.options_from_session(self.session)
        splits = conn.split_manager.splits(
            node.table, max(opts.threads, 1))
        lifespan = self.lifespan_splits.get(node)
        if lifespan is not None:
            # grouped execution: only this bucket's splits this pass
            splits = lifespan
        restrict = getattr(self, "split_restrict", None)
        if restrict is not None:
            pred = restrict.get((node.catalog, node.table.table))
            if pred is not None:
                # result-cache delta run: only the changed splits
                splits = [s for s in splits if pred(s)]
        import time as _time
        t_query0 = _time.perf_counter()

        def record_split(i: int, t0: float, batches: int) -> None:
            # per-split completion record (reference event/SplitMonitor)
            if self.stats is not None:
                self.stats.record_split(
                    node.table.table, i, t0 - t_query0,
                    _time.perf_counter() - t0, batches)

        yield from scancache.scan_splits(
            conn, node.catalog, list(node.columns), splits,
            current_pushdown, self.rows_per_batch, opts,
            record_split=record_split, check_cancel=self._check_cancel,
            stats=self.stats, static_pushdown=node.pushdown or None)

    def _ValuesNode(self, node: ValuesNode) -> Iterator[Batch]:
        data = {
            f.name: (f.type, [r[i] for r in node.rows])
            for i, f in enumerate(node.fields)
        }
        if node.fields:
            yield Batch.from_pydict(data)
            return
        # zero-column values (SELECT without FROM): a 1-row dummy column
        n = len(node.rows)
        mask = jnp.arange(bucket_capacity(max(n, 1))) < n
        yield Batch(Schema([]), [], mask)

    # -- streaming nodes ------------------------------------------------------
    compact_streams = True   # DistributedExecutor turns this off: compact()
    #                          on a mesh-sharded batch would gather it

    def _compactor(self):
        """Per-operator adaptive compaction (one host sync per checked
        batch): the analogue of Presto's compacted filter output pages
        (reference operator/project/PageProcessor.java). Selective
        filters/joins leave mostly-dead lanes, and every downstream
        sort-based kernel pays for capacity, not liveness. Checks batches
        >128K capacity; after the first batch that doesn't shrink >=4x it
        stops checking (selectivity is near-uniform across an operator's
        batches), so a non-selective stream pays exactly one sync."""
        state = {"check": self.compact_streams}

        def maybe_compact(b: Batch) -> Batch:
            # the 2^17 floor: below it, downstream kernels over the
            # uncompacted capacity cost less than the ~100ms tunnel RTT
            # of the liveness readback (measured: sub-128K operators were
            # paying 10x their kernel time in compaction syncs)
            if not state["check"] or b.capacity <= (1 << 17):
                return b
            with TRACER.span("device-sync", what="compaction-liveness"):
                tgt = bucket_capacity(b.host_count())
            if tgt * 4 <= b.capacity:
                return b.compact(tgt, check=False)
            state["check"] = False
            return b
        return maybe_compact

    def _FilterNode(self, node: FilterNode) -> Iterator[Batch]:
        pred = self._resolve(node.predicate)
        fn = compile_filter(pred, _plan_schema(node.child), errors=True)
        compact = self._compactor()
        for b in self.run(node.child):
            out, err = fn(b)
            if err is not None:
                self.error_flags.append(err)
            yield compact(out)

    def _ProjectNode(self, node: ProjectNode) -> Iterator[Batch]:
        exprs = [self._resolve(e) for e in node.exprs]
        fn = compile_projection(exprs, [f.name for f in node.fields],
                                _plan_schema(node.child), errors=True)
        for b in self.run(node.child):
            out, err = fn(b)
            if err is not None:
                self.error_flags.append(err)
            yield out

    def _LimitNode(self, node: LimitNode) -> Iterator[Batch]:
        remaining = node.count
        for b in self.run(node.child):
            if remaining <= 0:
                return
            out = limit_kernel(b, remaining)
            remaining -= out.host_count()
            yield out

    def _UnionNode(self, node: UnionNode) -> Iterator[Batch]:
        for c in node.children:
            yield from self.run(c)

    def _UnnestNode(self, node) -> Iterator[Batch]:
        exprs = tuple(self._resolve(e) for e in node.exprs)
        fn = unnest_expand_fn(exprs, node.ordinality, _plan_schema(node))
        compact = self._compactor()
        for b in self.run(node.child):
            out, err = fn(b)
            if err is not None:
                self.error_flags.append(err)
            yield compact(out)

    def _GroupIdNode(self, node: GroupIdNode) -> Iterator[Batch]:
        """One replica batch per grouping set: absent keys get their
        validity cleared (NULL), $group_id is a constant column
        (reference operator/GroupIdOperator.java)."""
        schema = _plan_schema(node)
        for b in self.run(node.child):
            dead = jnp.zeros_like(b.row_mask)
            alive = jnp.ones_like(b.row_mask)
            for g, s in enumerate(node.grouping_sets):
                cols = []
                for i, c in enumerate(b.columns):
                    if i < node.n_keys and i not in s:
                        # zero data too: the group-sort uses (null-rank,
                        # data) as sort operands, so stale data under a
                        # cleared validity would still split groups
                        cols.append(Column(c.type, jnp.zeros_like(c.data),
                                           dead, c.dictionary))
                    else:
                        cols.append(c)
                cols.append(Column(
                    T.BIGINT,
                    jnp.full(b.capacity, g, dtype=jnp.int64), alive, None))
                yield Batch(schema, cols, b.row_mask)

    # -- blocking nodes -------------------------------------------------------
    def _drain(self, node: PlanNode) -> Optional[Batch]:
        batches = list(self.run(node))
        if not batches:
            return None
        return batches[0] if len(batches) == 1 else concat_batches(batches)

    def _SortNode(self, node: SortNode) -> Iterator[Batch]:
        from .spill import SortSpillBuffer
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.keys]
        buf = SortSpillBuffer(self.pool, "order-by", keys)
        try:
            for b in self.run(node.child):
                buf.add(b)
            yield from buf.results(self.rows_per_batch)
        finally:
            buf.close()

    def _TopNNode(self, node: TopNNode) -> Iterator[Batch]:
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.keys]
        state: Optional[Batch] = None
        for b in self.run(node.child):
            cand = top_n(b, keys, node.count).compact(
                bucket_capacity(node.count))
            state = cand if state is None else top_n(
                concat_batches([state, cand]), keys, node.count).compact(
                    bucket_capacity(node.count))
        if state is not None:
            yield sort_batch(state, keys)

    def _WindowNode(self, node) -> Iterator[Batch]:
        from ..ops.window import WindowSpec, evaluate_window
        b = self._drain(node.child)
        if b is None:
            return
        specs = [WindowSpec(f.fn, f.args, f.output_type, f.name, f.offset,
                            f.ignore_order, f.frame, f.frame_start,
                            f.frame_end) for f in node.functions]
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.order_keys]
        out = evaluate_window(b, list(node.partition_indices), keys, specs)
        yield Batch(_plan_schema(node), out.columns, out.row_mask)

    def _MarkDistinctNode(self, node) -> Iterator[Batch]:
        """Drain + sort-based first-occurrence flags (the window/sort
        drain pattern; reference MarkDistinctOperator keeps a hash set
        across pages instead)."""
        from ..ops.aggregation import mark_distinct_flags
        b = self._drain(node.child)
        if b is None:
            return
        flags = mark_distinct_flags(b, list(node.cols))
        mark_col = Column(T.BOOLEAN, flags, b.row_mask, None)
        yield Batch(_plan_schema(node), list(b.columns) + [mark_col],
                    b.row_mask)

    def _grouped_partial_fn(self, group, aggs, kb):
        """Per-batch partial aggregation with the stats-bounds contract:
        record which kernel the grouping takes (once — the dispatch is
        shape-stable across an operator's batches), and when static key
        bounds are in play, append the device-side violation scalar to the
        error channel so a connector overclaiming its statistics fails the
        query instead of silently misgrouping (one sync per query)."""
        from ..ops.aggregation import dense_path_selected
        allow = bool_property(self.session, "dense_grouping", True)
        seen = {}

        def partial(b: Batch) -> Batch:
            # per-batch dispatch mirror: only batches that actually take
            # the dense path clamp out-of-bounds keys, so only those
            # batches owe a violation flag — the sort path groups any
            # key correctly and must not fail on overclaimed stats
            dense = allow and dense_path_selected(b, group, aggs,
                                                  key_bounds=kb)
            if not seen:
                seen["done"] = True
                (_AGG_DENSE_SELECTED if dense
                 else _AGG_SORT_SELECTED).inc()
            if dense and kb is not None:
                self.error_flags.append(
                    key_bounds_violation_jit(b, group, kb))
            return grouped_aggregate(b, group, aggs, mode="partial",
                                     key_bounds=kb, allow_dense=allow)
        return partial

    def _DistinctNode(self, node: DistinctNode) -> Iterator[Batch]:
        from .spill import AggSpillBuffer
        cols = list(range(len(node.fields)))
        kb = tuple(node.key_bounds) if node.key_bounds else None
        buf = AggSpillBuffer(
            self.pool, "distinct", cols, [], self.spill_partitions,
            key_bounds=kb,
            allow_dense=bool_property(self.session, "dense_grouping",
                                      True),
            error_sink=self.error_flags.append)
        partial = self._grouped_partial_fn(cols, [], kb)
        try:
            for b in self.run(node.child):
                buf.add_partial(partial(b))
            yield from buf.results()
        finally:
            buf.close()

    def _AggregationNode(self, node: AggregationNode) -> Iterator[Batch]:
        aggs = [
            AggSpec(a.fn, a.arg, a.output_type, a.name, mask=a.mask,
                    param=a.param)
            for a in node.aggs
        ]
        for a in node.aggs:
            if a.distinct:
                raise NotImplementedError(
                    "DISTINCT aggregates must be lowered by the planner")
        group = list(node.group_indices)
        from ..ops.aggregation import percentile_drains
        # final-step nodes consume STATE columns (the fragmenter decided
        # drain-vs-sketch before splitting; agg input indices reference
        # the raw child, not the state layout) — never re-check them
        if node.step != "final" and \
                percentile_drains(aggs, _plan_schema(node.child).types,
                                  bool(group)):
            # grouped/string approx_percentile: no mergeable state —
            # drain the input and evaluate in one exact segmented-sort
            # pass (global numeric forms carry bounded qdigest-style
            # histogram state through the normal partial/final path
            # below instead)
            b = self._drain(node.child)
            if b is None:
                if group:
                    return
                b = Batch.from_arrays(
                    _plan_schema(node.child),
                    [[] for _ in node.child.fields], num_rows=0)
            if group:
                yield grouped_aggregate(b, group, aggs, mode="single")
            else:
                yield global_aggregate(b, aggs, mode="single")
            return
        # fragment steps (reference plan/AggregationNode.Step): SINGLE
        # raw->rows; PARTIAL raw->states (shipped to an exchange); FINAL
        # states->rows.  step never changes the kernels, only which side
        # of the state boundary this node covers.
        step = node.step
        if not group:
            # sketch aggregates carry wide state tiles ([cap, m]
            # registers / [cap, bins] histograms); merge them eagerly so
            # peak memory stays a few tiles, not 64
            merge_at = 4 if any(a.fn in ("approx_distinct",
                                         "approx_percentile")
                                for a in aggs) else 64
            parts: List[Batch] = []
            for b in self.run(node.child):
                parts.append(global_aggregate(b, aggs, mode="partial")
                             if step != "final" else b)
                if len(parts) >= merge_at:
                    parts = [global_aggregate(concat_batches(parts), aggs,
                                              mode="merge")]
            if not parts:
                # no input still finalizes to one row (count=0): final
                # mode reduces a 0-row state batch; other steps reduce a
                # 0-row raw batch into an explicit empty partial
                empty = Batch.from_arrays(
                    _plan_schema(node.child),
                    [[] for _ in node.child.fields], num_rows=0)
                parts = [empty if step == "final"
                         else global_aggregate(empty, aggs,
                                               mode="partial")]
            states = (concat_batches(parts) if len(parts) > 1 else parts[0])
            if step == "partial":
                yield global_aggregate(states, aggs, mode="merge") \
                    if len(parts) > 1 else states
            else:
                yield global_aggregate(states, aggs, mode="final")
            return
        # grouped: partial per input batch, hierarchical merge (spillable
        # state, hash-partitioned by group keys under memory pressure),
        # final per state / per spilled partition. With task_concurrency
        # > 1, partials run on N driver threads over a round-robin local
        # exchange (reference AddLocalExchanges + multi-driver pipelines)
        from .local_exchange import parallel_drivers
        from .spill import AggSpillBuffer
        key_idx = list(range(len(group)))
        kb = tuple(node.key_bounds) if node.key_bounds else None
        buf = AggSpillBuffer(
            self.pool, "hash-agg", key_idx, aggs, self.spill_partitions,
            key_bounds=kb,
            allow_dense=bool_property(self.session, "dense_grouping",
                                      True),
            error_sink=self.error_flags.append)
        concurrency = int(self.session.properties.get(
            "task_concurrency", 1))
        try:
            if step == "final":
                partials = self.run(node.child)
            else:
                partials = parallel_drivers(
                    self.run(node.child),
                    self._grouped_partial_fn(group, aggs, kb),
                    concurrency)
            for p in partials:
                buf.add_partial(p)
            if node.default_gids and step in ("single", "final"):
                # grouping sets over EMPTY input: the empty sets still
                # owe their grand-total rows (reference
                # AggregationNode.hasDefaultOutput); detect zero output
                # groups (aggregated outputs are small, so the host
                # count is cheap) and synthesize them
                outs = list(buf.results(final=True))
                live = sum(b.host_count() for b in outs)
                yield from outs
                if live == 0:
                    yield _default_grouping_batch(node)
            else:
                yield from buf.results(final=step != "partial")
        finally:
            buf.close()

    def _lifespan_partitions(self, node: JoinNode):
        """Partition-wise (grouped / lifespan) execution check: when both
        join sides scan hive-partitioned tables whose partition keys are
        covered pairwise by the equi-join keys, rows only ever match
        within equal partition values — so the join can run one bucket
        at a time, bounding peak HBM at O(bucket) instead of O(table)
        (reference execution/Lifespan.java:26,
        execution/scheduler/group/LifespanScheduler.java,
        PipelineExecutionStrategy.GROUPED_EXECUTION).

        Returns (left_scan, right_scan, ordered common partition value
        tuples) or None."""
        if node.join_type != "inner":
            return None
        if not bool_property(self.session, "grouped_execution", True):
            return None

        def unwrap(n):
            while isinstance(n, FilterNode):
                n = n.child
            return n if isinstance(n, TableScanNode) else None

        ls, rs = unwrap(node.left), unwrap(node.right)
        if ls is None or rs is None or ls is rs:
            return None
        # memoized (shared-subtree) scans cache their first bucket's
        # output; never lifespan-restrict them
        if any(n in self._ever_shared
               for n in (ls, rs, node.left, node.right)):
            return None

        def partition_info(scan):
            conn = self.session.catalogs.get(scan.catalog)
            keys_fn = getattr(conn, "partition_keys", None)
            if keys_fn is None:
                return None
            keys = keys_fn(scan.table.table)
            if not keys:
                return None
            try:
                idx = [scan.columns.index(k) for k in keys]
            except ValueError:
                return None     # partition column not even scanned
            # one split enumeration per side; bucket selection later is
            # a dict lookup, not a directory re-walk per bucket
            by_value: Dict[Tuple, List] = {}
            for s in conn.split_manager.splits(scan.table, 1):
                if len(s.info) > 1:
                    by_value.setdefault(tuple(s.info[1]), []).append(s)
            return idx, by_value

        li, ri = partition_info(ls), partition_info(rs)
        if li is None or ri is None or len(li[0]) != len(ri[0]):
            return None
        # every partition-key position must be an equi-join pair
        pairs = set(zip(node.left_keys, node.right_keys))
        if any((lk, rk) not in pairs
               for lk, rk in zip(li[0], ri[0])):
            return None
        common = sorted(li[1].keys() & ri[1].keys())
        return ls, rs, [(li[1][v], ri[1][v]) for v in common]

    def _coalesce(self, it: Iterator[Batch],
                  min_cap: int = 1 << 15) -> Iterator[Batch]:
        """Merge runs of small batches into fewer larger ones. Selective
        joins compact their outputs to tiny buckets; on a ~100ms-RTT
        tunneled device every downstream operator then pays dispatch
        latency PER BATCH, dwarfing its kernel time. Capacity (not a
        live-count sync) decides: batches at or above min_cap pass
        through, smaller ones buffer until their capacities sum past it
        (the role of the reference's PageBuffer/page coalescing in
        exchange clients)."""
        pend: List[Batch] = []
        acc = 0
        for b in it:
            if b.capacity >= min_cap:
                if pend:
                    yield (pend[0] if len(pend) == 1
                           else concat_batches(pend))
                    pend, acc = [], 0
                yield b
                continue
            pend.append(b)
            acc += b.capacity
            if acc >= min_cap:
                yield concat_batches(pend)
                pend, acc = [], 0
        if pend:
            yield pend[0] if len(pend) == 1 else concat_batches(pend)

    def _JoinNode(self, node: JoinNode) -> Iterator[Batch]:
        yield from self._coalesce(self._join_dispatch(node))

    def _join_dispatch(self, node: JoinNode) -> Iterator[Batch]:
        lifespan = self._lifespan_partitions(node)
        if lifespan is None and bool_property(self.session,
                                              "fused_pipeline", True):
            fused = self._try_fused_chain(node)
            if fused is not None:
                yield from fused
                return
        if lifespan is not None:
            ls, rs, buckets = lifespan
            for lsplits, rsplits in buckets:
                self.lifespan_splits[ls] = lsplits
                self.lifespan_splits[rs] = rsplits
                # dynamic-filter bounds are bucket-local: bounds pushed
                # while joining bucket k must not prune bucket k+1
                saved_dyn = dict(self.dynamic_pushdown)
                try:
                    yield from self._join_once(node)
                finally:
                    self.lifespan_splits.pop(ls, None)
                    self.lifespan_splits.pop(rs, None)
                    self.dynamic_pushdown = saved_dyn
            return
        yield from self._join_once(node)

    def _try_fused_chain(self, top: JoinNode):
        """Head check for whole-pipeline fusion (exec/fused.py): a chain
        of unique-build inner/left lookup joins with interleaved filters
        and projections over one streaming source fuses into ONE jitted
        program per probe batch. Returns the output iterator, or None
        when the shape doesn't qualify — skewed/non-unique builds,
        residual predicates, FULL OUTER, cross joins, shared interior
        subtrees — in which case the generic per-operator path runs
        unchanged. EXPLAIN ANALYZE attributes the fused chain's work to
        the head join (interior nodes never execute standalone)."""
        def join_ok(j: PlanNode) -> bool:
            return (isinstance(j, JoinNode)
                    and j.join_type in ("inner", "left")
                    and j.build_unique and j.residual is None)

        if not join_ok(top):
            return None
        nodes: List[PlanNode] = []       # top-down
        cur: PlanNode = top
        njoins = 0
        while True:
            if cur is not top and cur in self._shared:
                break                    # memoized source boundary
            if join_ok(cur):
                nodes.append(cur)
                njoins += 1
                cur = cur.left
            elif isinstance(cur, (FilterNode, ProjectNode)):
                nodes.append(cur)
                cur = cur.child
            else:
                break
        if njoins < 2:
            return None
        from ..expr.params import has_params
        if any(has_params(getattr(n, "predicate", None))
               or has_params(getattr(n, "exprs", None))
               for n in nodes):
            # plan-template parameters: the fused head/tail programs
            # trace expressions inside their own jits with no operand
            # channel for runtime bindings — run the generic
            # per-operator path (compile_filter/compile_projection
            # carry the bindings there)
            return None
        return self._run_fused_chain(nodes, cur)

    def _run_fused_chain(self, nodes: List[PlanNode], source: PlanNode):
        """Drain + prepare every build in the chain (bottom-up), push all
        dynamic-filter bounds to the source scan BEFORE it starts (the
        generic path can only push the bottom join's bounds), then stream
        the probe source through the fused programs.

        Selectivity-first execution: the HEAD program applies every
        hoistable key-bounds mask plus the first join's membership mask
        over the raw source lanes — no payload gathers — and carries the
        surviving-lane count as a traced scalar. The executor syncs a
        WINDOW of those counts in one readback, compacts each surviving
        batch to its live bucket, and only then runs the TAIL program
        (all the joins' payload gathers) over the compacted lanes. The
        greedy join order already put the most selective join first
        (planner selectivity ranking), so on a q27-shaped star chain the
        payload gathers touch ~1% of the source lanes instead of all
        2^20, and the per-probe-batch liveness RTT is amortized to
        1/window."""
        from .fused import JoinStage, fused_pipeline, fused_prefilter

        order = list(reversed(nodes))
        # current-schema index -> source-schema index (for scan pushdown)
        src_map = {i: i for i in range(len(source.fields))}
        scan_target = self._dynamic_scan_target(source) \
            if isinstance(source, TableScanNode) else None
        dyn_enabled = bool_property(self.session,
                                    "enable_dynamic_filtering", True)
        stages: List[object] = []
        preps: List[object] = []
        builds: List[Batch] = []
        dyns: List[jnp.ndarray] = []
        bufs: List = []
        pre_rows: List[Tuple[int, int, int]] = []

        def close_bufs() -> None:
            for bf in bufs:
                bf.close()

        try:
            ok = self._drain_fused_builds(
                order, src_map, scan_target, dyn_enabled, stages, preps,
                builds, dyns, bufs, pre_rows)
        except BaseException:
            close_bufs()
            raise
        if not ok:
            close_bufs()
            return None

        first_join = next(i for i, st in enumerate(stages)
                          if isinstance(st, JoinStage))
        head, tail = stages[:first_join], stages[first_join:]
        join1 = tail[0]
        semi_keys = ((join1.lkeys, join1.rkeys)
                     if join1.join_type == "inner" else None)
        pre_keys = tuple(k for k, _, _ in pre_rows)
        pre_vals = jnp.asarray([[lo, hi] for _, lo, hi in pre_rows],
                               dtype=jnp.int64).reshape(len(pre_rows), 2)
        fn_head = fused_prefilter(tuple(head), pre_keys, semi_keys)
        fn_tail = fused_pipeline(tuple(tail))
        preps_t, builds_t, dyns_t = tuple(preps), tuple(builds), tuple(dyns)
        window = max(1, int(self.session.properties.get(
            "fused_compact_window", 4)))
        return self._stream_fused(fn_head, fn_tail, source, pre_vals,
                                  preps_t, builds_t, dyns_t, window,
                                  close_bufs, tail_stages=tuple(tail))

    def _stream_fused(self, fn_head, fn_tail, source, pre_vals, preps_t,
                      builds_t, dyns_t, window, close_bufs,
                      tail_stages=()) -> Iterator[Batch]:
        """Head -> windowed compaction -> tail streaming loop. One
        liveness readback per ``window`` probe batches (the head carries
        each batch's live count as a traced scalar); the check disables
        itself after a window with no >=4x shrink, mirroring
        _compactor's adaptive semantics, so a non-selective chain pays
        exactly one sync."""
        import numpy as np

        from ..ops.jitcache import compact_jit
        compact = self._compactor()
        state = {"check": self.compact_streams}
        pend: List[Tuple[Batch, jnp.ndarray]] = []
        # same 2^17 floor as _compactor: below it the tail kernels over
        # uncompacted capacity cost less than the (already amortized)
        # liveness RTT. Session-overridable so tests exercise the path
        # at CPU-friendly sizes.
        floor = int(self.session.properties.get(
            "fused_compact_floor", 1 << 17))

        def drain_pend() -> List[Batch]:
            if not pend:
                return []
            with TRACER.span("device-sync", what="fused-liveness",
                             batches=len(pend)):
                counts = np.asarray(jnp.stack([c for _, c in pend]))
            outs, shrunk = [], False
            for (b, _), live in zip(pend, counts):
                tgt = bucket_capacity(max(int(live), 1))
                if b.capacity > floor and tgt * 4 <= b.capacity:
                    b = compact_jit(b, tgt)
                    shrunk = True
                outs.append(b)
            if not shrunk:
                # selectivity is near-uniform across a chain's batches:
                # nothing shrank this window, so later windows won't
                state["check"] = False
            pend.clear()
            return outs

        tail_fn = {"fn": fn_tail}
        has_pallas = any(getattr(st, "pallas", False)
                         for st in tail_stages)

        def run_tail(hb: Batch) -> Iterator[Batch]:
            _FUSED_TAIL_LANES.inc(hb.capacity)
            try:
                out, err = tail_fn["fn"](hb, preps_t, builds_t, dyns_t)
            except Exception as e:
                # a Pallas stage that fails to lower falls back to the
                # pure-XLA chain for this and every later batch (the
                # ops/pallas_join breaker) — any other failure is real
                from ..ops import pallas_join as PJ
                if not has_pallas or PJ.FORCE_PALLAS_PROBE:
                    raise
                from .fused import fused_pipeline, strip_pallas
                stripped = fused_pipeline(strip_pallas(tail_stages))
                # stripped rerun FIRST: a failure that also breaks the
                # XLA chain (OOM, an upstream-stage bug) propagates
                # without tripping the process-wide breaker
                out, err = stripped(hb, preps_t, builds_t, dyns_t)
                PJ.note_kernel_failure(e)
                tail_fn["fn"] = stripped
            if err is not None:
                self.error_flags.append(err)
            yield compact(out)

        try:
            for probe in self.run(source):
                _FUSED_SOURCE_LANES.inc(probe.capacity)
                hb, err, cnt = fn_head(probe, pre_vals, builds_t[0],
                                       preps_t[0])
                if err is not None:
                    self.error_flags.append(err)
                if not state["check"] or hb.capacity <= floor:
                    # sub-floor batches can never compact (the tail over
                    # their full capacity costs less than the readback):
                    # bypass the window WITHOUT syncing or tripping the
                    # adaptive disable, mirroring _compactor's skip
                    yield from run_tail(hb)
                    continue
                pend.append((hb, cnt))
                if len(pend) >= window:
                    for b in drain_pend():
                        yield from run_tail(b)
            for b in drain_pend():
                yield from run_tail(b)
        finally:
            close_bufs()

    def _drain_fused_builds(self, order, src_map, scan_target, dyn_enabled,
                            stages, preps, builds, dyns, bufs,
                            pre_rows) -> bool:
        """Drain + prepare every build of a fused chain, appending to the
        caller's lists; False = shape disqualified (empty/spilled build),
        fall back to the generic path. ``pre_rows`` collects every
        dynamic-filter bound that maps to a raw source column —
        (source index, lo, hi) — for the head program's
        before-any-gathers mask."""
        from .fused import FilterStage, JoinStage, ProjectStage
        from .spill import HostPartitionStore, SpillableBuildBuffer

        for nd in order:
            if isinstance(nd, FilterNode):
                stages.append(FilterStage(self._resolve(nd.predicate)))
                continue
            if isinstance(nd, ProjectNode):
                exprs = tuple(self._resolve(e) for e in nd.exprs)
                stages.append(ProjectStage(
                    exprs, tuple(f.name for f in nd.fields)))
                new_map = {}
                for out_i, e in enumerate(exprs):
                    if isinstance(e, ir.InputRef) and e.index in src_map:
                        new_map[out_i] = src_map[e.index]
                src_map = new_map
                continue
            # JoinStage: drain + prepare this build now (through the
            # spillable buffer for memory accounting; a build the pool
            # forces to host can't fuse — generic path re-drains it)
            buf = SpillableBuildBuffer(self.pool, "join-build",
                                       list(nd.right_keys),
                                       self.spill_partitions)
            bufs.append(buf)
            for b in self.run(nd.right):
                buf.add(b)
            build = buf.finish()
            if build is None or isinstance(build, HostPartitionStore):
                return False             # empty/spilled: generic path
            summary = self._build_summary(build, nd.right_keys)
            if int(summary[0]) == 0:
                return False
            scap = bucket_capacity(max(int(summary[0]), 1))
            if scap < build.capacity:
                from ..ops.jitcache import compact_jit
                build = compact_jit(build, scap)
            prep = self._prepare_join_build(build, nd.right_keys,
                                            summary=summary,
                                            key_bounds=nd.key_bounds)
            from ..ops import pallas_join as PJ
            from ..ops.join import is_direct_prepared
            payload_cols = tuple(range(len(nd.right.fields)))
            use_pallas = (self._pallas_probe_on()
                          and PJ.supports_join(prep, build,
                                               payload_cols))
            _note_join_strategy(
                self.stats, nd,
                "direct" if is_direct_prepared(prep) else "sorted",
                nd.distribution)
            dyn_keys: Tuple[int, ...] = ()
            dyn_val = jnp.zeros((0, 2), dtype=jnp.int64)
            if nd.join_type == "inner" and dyn_enabled:
                bounds = self._summary_bounds(summary, nd.left_keys)
                if bounds:
                    dyn_keys = tuple(k for k, _, _ in bounds)
                    dyn_val = jnp.asarray([[lo, hi]
                                           for _, lo, hi in bounds],
                                          dtype=jnp.int64)
                    for k, lo, hi in bounds:
                        # bounds whose key survives untouched back to the
                        # raw source schema hoist to the head program's
                        # pre-gather mask (selectivity-first)
                        si = src_map.get(k)
                        if si is not None:
                            pre_rows.append((si, lo, hi))
                    if scan_target is not None:
                        scan, smap = scan_target
                        extra = []
                        for k, lo, hi in bounds:
                            si = src_map.get(k)
                            si = smap.get(si) if si is not None else None
                            if si is not None:
                                extra.append((scan.columns[si], lo, hi))
                        if extra:
                            self.dynamic_pushdown.setdefault(
                                scan, []).extend(extra)
            stages.append(JoinStage(
                lkeys=tuple(nd.left_keys), rkeys=tuple(nd.right_keys),
                payload=payload_cols,
                names=tuple(f"$b{i}"
                            for i in range(len(nd.right.fields))),
                join_type=nd.join_type,
                out_fields=tuple((f.name, f.type) for f in nd.fields),
                dyn_keys=dyn_keys,
                pallas=use_pallas))
            preps.append(prep)
            builds.append(build)
            dyns.append(dyn_val)
        return True

    def _join_once(self, node: JoinNode) -> Iterator[Batch]:
        payload = list(range(len(node.right.fields)))
        payload_names = [f"$b{i}" for i in payload]
        if node.join_type == "cross":
            yield from self._cross_join(node, self._drain(node.right))
            return
        residual = (self._resolve(node.residual)
                    if node.residual is not None else None)
        residual_fn = None
        residual_outer = None
        if residual is not None:
            if node.join_type in ("left", "full"):
                # ON-clause filter of an outer join: gates matches, never
                # drops probe rows (_probe_outer_residual)
                residual_outer = self.checked_filter(
                    residual, _plan_schema(node))
            else:
                residual_fn = self.checked_filter(residual,
                                                  _plan_schema(node))

        from .local_exchange import exchange_source
        from .spill import HostPartitionStore, SpillableBuildBuffer
        buf = SpillableBuildBuffer(self.pool, "join-build",
                                   list(node.right_keys),
                                   self.spill_partitions)
        # inter-pipeline overlap: start the probe side's scan/decode in a
        # background producer while the build side drains — the role of
        # the reference's concurrently-running build and probe pipelines
        # within one task (PhasedExecutionSchedule starts both stages)
        probe_ex = None
        # don't prefetch a probe whose scan a dynamic filter could prune:
        # starting the scan before the build side finishes would read the
        # splits before the bounds exist (the reference equally delays the
        # probe scan while dynamic filters are being collected)
        dyn_prunable = (
            node.join_type == "inner"
            and bool_property(self.session, "enable_dynamic_filtering",
                              True)
            and self._dynamic_scan_target(node.left) is not None)
        if (bool_property(self.session, "probe_prefetch", True)
                and not dyn_prunable):
            probe_ex = exchange_source(self.run(node.left), "single", 1,
                                       buffer_batches=4)

        def probe_stream() -> Iterator[Batch]:
            return (probe_ex.consumer(0) if probe_ex is not None
                    else self.run(node.left))
        try:
            for b in self.run(node.right):
                buf.add(b)
            build = buf.finish()
            if isinstance(build, HostPartitionStore):
                yield from self._partitioned_join(
                    node, build, payload, payload_names, residual_fn,
                    probe_stream(), residual_outer=residual_outer)
                return
            dyn = None
            summary = None
            if build is not None:
                # ONE fused readback for live count + per-key bounds: the
                # tunneled backend pays a full RTT per sync, so the
                # compaction size, direct-table bounds, and dynamic-filter
                # bounds all come from the same device reduction
                summary = self._build_summary(build, node.right_keys)
            if (node.join_type == "inner" and summary is not None
                    and bool_property(self.session,
                                      "enable_dynamic_filtering", True)):
                dyn = self._summary_bounds(summary, node.left_keys)
                if dyn:
                    self._push_dynamic_bounds(node.left, dyn)
            compact = self._compactor()
            track_full = node.join_type == "full" and build is not None
            build_matched = None
            full_acc = ({"m": None} if track_full
                        and residual_outer is not None else None)
            if build is not None:
                # compact a sparse build before sorting it: probe-side
                # binary searches walk a table sized by CAPACITY, so a
                # 10%-live build would cost 10x the gathers it needs
                # (reference PagesIndex compacts build pages the same way)
                scap = bucket_capacity(max(int(summary[0]), 1))
                if scap < build.capacity:
                    from ..ops.jitcache import compact_jit
                    build = compact_jit(build, scap)
            prep = (self._prepare_join_build(build, node.right_keys,
                                             summary=summary,
                                             key_bounds=node.key_bounds)
                    if build is not None else None)
            if build is not None:
                from ..ops.join import is_direct_prepared
                _note_join_strategy(
                    self.stats, node,
                    ("direct" if is_direct_prepared(prep) else "sorted")
                    if node.build_unique else "expand",
                    node.distribution)
            # ONE build-side multiplicity readback replaces the per-probe-
            # batch match_count_max sync (each a tunnel RTT): the max key
            # multiplicity of the build bounds every probe batch's match
            # count, so the static expansion factor is known up front
            maxk_bound = (self._build_multiplicity(prep)
                          if build is not None and not node.build_unique
                          else None)
            for probe in probe_stream():
                if build is None:
                    if node.join_type == "inner":
                        continue
                    out = self._null_extend(probe, node)
                else:
                    if dyn:
                        probe = _apply_dynamic_bounds(probe, dyn)
                    if residual_outer is not None:
                        for out in self._probe_outer_residual(
                                node, probe, build, payload,
                                payload_names, prep, residual_outer,
                                full_acc, maxk=maxk_bound):
                            yield compact(out)
                    else:
                        for out in self._probe_batches(
                                node, probe, build, payload,
                                payload_names, prep, maxk=maxk_bound):
                            if residual_fn is not None:
                                out = residual_fn(out)
                            yield compact(out)
                        if track_full:
                            m = build_match_mask_jit(
                                probe, build, list(node.left_keys),
                                list(node.right_keys), prep)
                            build_matched = (m if build_matched is None
                                             else build_matched | m)
                    continue
                if residual_fn is not None:
                    out = residual_fn(out)
                yield compact(out)
            if track_full:
                # FULL OUTER tail: build rows no probe row ever matched,
                # null-extended on the probe side (reference
                # LookupOuterOperator over the visited-positions bitmap)
                if full_acc is not None:
                    build_matched = full_acc["m"]
                yield compact(self._null_extend_build(
                    build, node, build_matched))
        finally:
            if probe_ex is not None:
                probe_ex.close()
            buf.close()

    def _dynamic_scan_target(self, probe: PlanNode):
        """(scan node, out-index -> scan-column mapping) when the probe
        chain maps columns straight to a scan through filters and identity
        projections; None otherwise."""
        mapping = {i: i for i in range(len(probe.fields))}
        node = probe
        while True:
            if node in self._ever_shared:
                return None  # replayed subtree feeds other consumers too
            if isinstance(node, FilterNode):
                node = node.child
                continue
            if isinstance(node, ProjectNode):
                new_map = {}
                for out_i, in_i in mapping.items():
                    e = node.exprs[in_i]
                    if isinstance(e, ir.InputRef):
                        new_map[out_i] = e.index
                mapping = new_map
                node = node.child
                continue
            break
        if not isinstance(node, TableScanNode) or not mapping:
            return None
        return node, mapping

    def _push_dynamic_bounds(self, probe: PlanNode,
                             dyn: List[Tuple[int, int, int]]) -> None:
        """Runtime scan pushdown: if the probe chain maps the join keys
        straight to scan columns (identity projections only), hand the
        build side's [lo, hi] to the scan so connectors prune on stats
        (reference sql/DynamicFilters.java:43 + the probe-side filter of
        LocalDynamicFiltersCollector; v319 collects build-side values and
        filters the probe scan the same way)."""
        target = self._dynamic_scan_target(probe)
        if target is None:
            return
        node, mapping = target
        extra = []
        for key_idx, lo, hi in dyn:
            scan_i = mapping.get(key_idx)
            if scan_i is None:
                continue
            extra.append((node.columns[scan_i], lo, hi))
        if extra:
            self.dynamic_pushdown.setdefault(node, []).extend(extra)

    def _partitioned_join(self, node: JoinNode, store, payload,
                          payload_names, residual_fn,
                          probe_batches: Optional[Iterator[Batch]] = None,
                          residual_outer=None) -> Iterator[Batch]:
        """Spilled-build probe: stage the probe side host-partitioned by
        the same key hash, then join partition-serially so only one build
        partition plus one probe chunk is device-resident at a time
        (reference GenericPartitioningSpiller.java probe protocol)."""
        from .spill import HostPartitionStore
        pstore: Optional[HostPartitionStore] = None
        # spilled builds join partition-serially over the sorted path:
        # a K-slot direct table per partition would multiply the very
        # memory pressure that forced the spill
        _note_join_strategy(
            self.stats, node,
            "sorted" if node.build_unique else "expand", "partitioned")
        if probe_batches is None:
            probe_batches = self.run(node.left)
        try:
            for probe in probe_batches:
                if pstore is None:
                    pstore = HostPartitionStore(probe.schema, store.n,
                                                pool=self.pool)
                pstore.add(probe, list(node.left_keys))
            if pstore is None:
                if node.join_type == "full":
                    # no probe rows at all: every build row is unmatched
                    for p in range(store.n):
                        bpart = store.partition_batch(p)
                        if bpart is not None:
                            yield self._null_extend_build(bpart, node, None)
                return
            for p in range(store.n):
                bpart = store.partition_batch(p)
                part_matched = None
                part_prep = None
                part_maxk = None
                for probe_p in pstore.partition_batches(
                        p, self.rows_per_batch):
                    if bpart is None:
                        if node.join_type in ("left", "full"):
                            yield self._null_extend(probe_p, node)
                        continue
                    if part_prep is None:
                        part_prep = self._prepare_join_build(
                            bpart, node.right_keys)
                        if not node.build_unique:
                            part_maxk = self._build_multiplicity(part_prep)
                    if residual_outer is not None:
                        # each probe row hashes to exactly one partition,
                        # so per-partition outer semantics compose to the
                        # global outer join
                        part_acc = ({"m": None}
                                    if node.join_type == "full" else None)
                        for out in self._probe_outer_residual(
                                node, probe_p, bpart, payload,
                                payload_names, part_prep, residual_outer,
                                part_acc, maxk=part_maxk):
                            yield out
                        if part_acc is not None \
                                and part_acc["m"] is not None:
                            part_matched = (
                                part_acc["m"] if part_matched is None
                                else part_matched | part_acc["m"])
                        continue
                    for out in self._probe_batches(node, probe_p, bpart,
                                                   payload, payload_names,
                                                   part_prep,
                                                   maxk=part_maxk):
                        yield residual_fn(out) if residual_fn is not None \
                            else out
                    if node.join_type == "full":
                        m = build_match_mask_jit(probe_p, bpart,
                                                 list(node.left_keys),
                                                 list(node.right_keys),
                                                 part_prep)
                        part_matched = (m if part_matched is None
                                        else part_matched | m)
                if node.join_type == "full" and bpart is not None:
                    yield self._null_extend_build(bpart, node,
                                                  part_matched)
        finally:
            if pstore is not None:
                pstore.close()

    #: per-kernel expansion cap: one skewed key would otherwise scale the
    #: expand_join output (probe_capacity x max_matches) without bound;
    #: past this the executor slices the build into bounded-multiplicity
    #: chunks via build_key_ranks
    SKEW_MATCH_LIMIT = 64

    #: largest (max-min+1) key span served by a direct-address lookup
    #: table (2^26 slots x 2 x i32 = 512MB of HBM); wider spans fall back
    #: to the composite binary search
    DIRECT_SPAN_LIMIT = 1 << 26

    def _build_summary(self, build: Batch, keys):
        """Host copy of the fused build reduction: [live_count,
        lo_0, hi_0, lo_1, hi_1, ...] over the given key columns (one
        readback; see ops/jitcache.py build_summary_jit)."""
        import numpy as np

        from ..ops.jitcache import build_summary_jit
        int_flags = tuple(isinstance(build.columns[k].type, _DYN_TYPES)
                          for k in keys)
        with TRACER.span("device-sync", what="build-summary"):
            return np.asarray(
                build_summary_jit(build, tuple(keys), int_flags))

    @staticmethod
    def _summary_bounds(summary, out_keys):
        """[(out_key, lo, hi), ...] for the integer keys in a summary
        (non-integer keys carry the (0, -1) empty sentinel)."""
        out = []
        for i, pk in enumerate(out_keys):
            lo, hi = int(summary[1 + 2 * i]), int(summary[2 + 2 * i])
            if lo <= hi:
                out.append((pk, lo, hi))
        return out

    def _prepare_join_build(self, build: Batch, keys, summary=None,
                            key_bounds=()):
        """LookupSource choice (reference HashBuilderOperator's
        BigintGroupByHash-vs-MultiChannel split), stats-first:

        1. planner-promised ``key_bounds`` (JoinNode.key_bounds, any
           arity) build a mixed-radix composite direct-address table
           with PLAN-TIME-KNOWN capacity — stable executable shapes
           across batches and queries sharing the plan. The build batch
           is cross-checked against the promised bounds through the
           row-error channel (STATS_BOUND_VIOLATION — the dense-group
           contract: stats that lie fail the query, never corrupt it);
        2. a single integer key with a bounded MEASURED range gets the
           runtime direct table (bounds from the caller's fused build
           summary, no extra sync);
        3. anything else gets the sorted composite search.

        Direct tables answer a probe key in TWO gathers independent of
        build size, where the sorted path pays O(log n) random gathers
        per probe lane — the dominant join cost on this hardware."""
        keys = tuple(keys)
        if key_bounds and bool_property(self.session, "join_dense_path",
                                        True):
            from ..ops.join import direct_keyed_plan
            plan = direct_keyed_plan(tuple(key_bounds))
            if plan is not None:
                los, sizes, K = plan
                self.error_flags.append(key_bounds_violation_jit(
                    build, keys, tuple(key_bounds)))
                return prepare_direct_keyed_jit(build, keys, los, sizes,
                                                bucket_capacity(K))
        if len(keys) == 1 and isinstance(build.columns[keys[0]].type,
                                         _DYN_TYPES):
            if summary is None:
                summary = self._build_summary(build, keys)
            if int(summary[0]) > 0:
                lo, hi = int(summary[1]), int(summary[2])
                span = hi - lo + 1
                if 0 < span <= self.DIRECT_SPAN_LIMIT:
                    return prepare_direct_jit(
                        build, keys, lo, bucket_capacity(span))
        return prepare_build_jit(build, keys)

    def _pallas_probe_on(self) -> bool:
        return bool_property(self.session, "join_pallas_probe", True)

    def _dispatch_lookup(self, probe: Batch, build: Batch, lkeys, rkeys,
                         payload, payload_names, jt: str, prepared):
        """Unique-build probe dispatch: the Pallas fused probe kernel
        when the session/backend/VMEM gate admits it, the XLA gather
        path otherwise. The FIRST kernel dispatch that fails to lower
        trips the process-wide breaker (ops/pallas_join) and this very
        batch transparently re-runs on XLA — an unproven Mosaic
        lowering can cost one failed compile, never a failed query."""
        from ..ops import pallas_join as PJ
        if self._pallas_probe_on() and PJ.supports_join(prepared, build,
                                                        payload):
            try:
                return lookup_join_pallas_jit(
                    probe, build, lkeys, rkeys, payload, payload_names,
                    jt, prepared)
            except Exception as e:
                if PJ.FORCE_PALLAS_PROBE:
                    raise      # tests want kernel failures loud
                # XLA rerun FIRST: only when it succeeds is the kernel
                # proven at fault — a failure that also breaks the XLA
                # path (OOM, a bug upstream) propagates from it without
                # tripping the process-wide breaker
                out = lookup_join_jit(probe, build, lkeys, rkeys,
                                      payload, payload_names, jt,
                                      prepared)
                PJ.note_kernel_failure(e)
                return out
        return lookup_join_jit(probe, build, lkeys, rkeys, payload,
                               payload_names, jt, prepared)

    def _build_multiplicity(self, prepared) -> Optional[int]:
        """Host int of the build's max key multiplicity (one readback,
        amortized over every probe batch of the join) — or None when the
        build is skewed past SKEW_MATCH_LIMIT. The bound is only used to
        size expand_join when it is SMALL: for a skewed build, sizing
        every probe batch by the hottest key would push all batches into
        the chunked skew path (most probe batches never touch the hot
        key), so those fall back to the per-batch match_count_max sync."""
        from ..ops.jitcache import max_multiplicity_jit
        with TRACER.span("device-sync", what="build-multiplicity"):
            m = int(max_multiplicity_jit(prepared))
        return m if m <= self.SKEW_MATCH_LIMIT else None

    def _probe_batches(self, node: JoinNode, probe: Batch, build: Batch,
                       payload, payload_names,
                       prepared=None, maxk=None) -> Iterator[Batch]:
        schema = _plan_schema(node)
        lkeys, rkeys = list(node.left_keys), list(node.right_keys)
        if prepared is None:
            prepared = prepare_build_jit(build, rkeys)
        # FULL OUTER probes like LEFT; the executor emits the
        # unmatched-build tail separately
        jt = "left" if node.join_type == "full" else node.join_type
        if node.build_unique:
            out = self._dispatch_lookup(probe, build, lkeys, rkeys,
                                        payload, payload_names, jt,
                                        prepared)
            yield Batch(schema, out.columns, out.row_mask)
            return
        if maxk is None:
            # skewed build (or standalone call): per-probe-batch count —
            # only batches that actually hit the hot key pay the chunked
            # skew loop below
            maxk = int(match_count_max_jit(probe, build, lkeys, rkeys,
                                           prepared))
        limit = self.SKEW_MATCH_LIMIT
        if maxk <= limit:
            out = expand_join_jit(
                probe, build, lkeys, rkeys, payload, payload_names, jt,
                bucket_capacity(max(maxk, 1), minimum=1), prepared)
            yield Batch(schema, out.columns, out.row_mask)
            return
        # skew fallback: chunk the build by within-key occurrence rank so
        # each expand stays bounded. Ranks are dense from 0, so a probe
        # row with any match always matches in chunk 0 — chunk 0 keeps the
        # outer-join behavior, later chunks join inner.
        ranks = build_key_ranks_jit(build, rkeys, prepared)
        for c in range(0, maxk, limit):
            sub = Batch(build.schema, build.columns,
                        build.row_mask & (ranks >= c)
                        & (ranks < c + limit))
            out = expand_join_jit(
                probe, sub, lkeys, rkeys, payload, payload_names,
                jt if c == 0 else "inner", limit, None)
            yield Batch(schema, out.columns, out.row_mask)

    def _probe_outer_residual(self, node: JoinNode, probe: Batch,
                              build: Batch, payload, payload_names,
                              prepared, residual_fn,
                              full_acc, maxk=None) -> Iterator[Batch]:
        """LEFT/FULL OUTER probe with a residual (join-filter) predicate:
        a probe row pairs with the build rows whose keys match AND whose
        residual passes; a probe row with no surviving match is
        reinstated null-extended (reference LookupJoinOperator +
        sql/gen/JoinFilterFunctionCompiler.java semantics: the ON filter
        gates matches, never drops probe rows). ``full_acc`` (FULL only)
        accumulates the build rows with at least one SURVIVING match for
        the unmatched-build tail.

        The residual runs only over matched lanes (row_mask = match), so
        its row-error channel fires exactly for rows the filter really
        evaluates — identical semantics on every executor."""
        from ..ops.jitcache import (expand_match_origins_jit,
                                    unique_match_build_mask_jit)
        schema = _plan_schema(node)
        lkeys, rkeys = list(node.left_keys), list(node.right_keys)
        npro = len(node.left.fields)

        def mark_full(mask):
            if full_acc is not None:
                full_acc["m"] = mask if full_acc["m"] is None \
                    else full_acc["m"] | mask

        if node.build_unique:
            out = self._dispatch_lookup(probe, build, lkeys, rkeys,
                                        payload, payload_names, "left",
                                        prepared)
            match = semi_join_mask_jit(probe, build, lkeys, rkeys,
                                       False, False, prepared)
            gated = residual_fn(Batch(schema, out.columns,
                                      probe.row_mask & match))
            survived = gated.row_mask
            cols = list(out.columns[:npro])
            for c in out.columns[npro:]:
                cols.append(Column(c.type, c.data,
                                   c.validity & survived, c.dictionary))
            if full_acc is not None:
                mark_full(unique_match_build_mask_jit(
                    probe, build, lkeys, rkeys, survived, prepared))
            yield Batch(schema, cols, probe.row_mask)
            return

        if maxk is None:
            maxk = int(match_count_max_jit(probe, build, lkeys, rkeys,
                                           prepared))
        limit = self.SKEW_MATCH_LIMIT
        if maxk <= limit:
            subs = [(build, bucket_capacity(max(maxk, 1), minimum=1),
                     prepared)]
        else:
            ranks = build_key_ranks_jit(build, rkeys, prepared)
            subs = [(Batch(build.schema, build.columns,
                           build.row_mask & (ranks >= c)
                           & (ranks < c + limit)), limit, None)
                    for c in range(0, maxk, limit)]
        has_survivor = None
        for sub, k, prep_c in subs:
            e = expand_join_jit(probe, sub, lkeys, rkeys, payload,
                                payload_names, "inner", k, prep_c)
            gated = residual_fn(Batch(schema, e.columns, e.row_mask))
            survived = gated.row_mask
            hs = jnp.any(survived.reshape(k, probe.capacity), axis=0)
            has_survivor = hs if has_survivor is None \
                else has_survivor | hs
            if full_acc is not None:
                orig, _ = expand_match_origins_jit(
                    probe, sub, lkeys, rkeys, k, prep_c)
                n = sub.capacity
                mark_full(jnp.zeros(n, dtype=bool).at[
                    jnp.where(survived, orig, n)].max(
                        survived, mode="drop"))
            yield Batch(schema, e.columns, survived)
        # reinstate probe rows with no surviving match, null-extended
        reinstated = self._null_extend(probe, node)
        yield Batch(schema, reinstated.columns,
                    probe.row_mask & ~(has_survivor
                                       if has_survivor is not None
                                       else jnp.zeros_like(
                                           probe.row_mask)))

    def _null_extend_build(self, build: Batch, node: JoinNode,
                           matched) -> Batch:
        """Unmatched build rows as output rows with NULL probe columns."""
        cap = build.capacity
        mask = build.row_mask
        if matched is not None:
            mask = mask & ~matched
        novalid = jnp.zeros(cap, dtype=bool)
        cols = []
        for f in node.left.fields:
            cols.append(Column(
                f.type, jnp.zeros(cap, dtype=f.type.storage_dtype),
                novalid, () if f.type.is_string else None))
        cols.extend(build.columns)
        return Batch(_plan_schema(node), cols, mask)

    def _null_extend(self, probe: Batch, node: JoinNode) -> Batch:
        cols = list(probe.columns)
        novalid = jnp.zeros_like(probe.row_mask)
        for f in node.fields[len(node.left.fields):]:
            cols.append(Column(
                f.type, jnp.zeros(probe.capacity, dtype=f.type.storage_dtype),
                novalid, () if f.type.is_string else None))
        return Batch(_plan_schema(node), cols, probe.row_mask)

    def _cross_join(self, node: JoinNode, build: Optional[Batch]
                    ) -> Iterator[Batch]:
        """Cross join where one side is tiny (scalar subqueries, VALUES)."""
        if build is None:
            return
        build = build.compact()
        nb = build.host_count()
        if nb == 0:
            return
        for probe in self.run(node.left):
            cap = probe.capacity
            reps: List[Batch] = []
            for k in range(nb):
                cols = list(probe.columns)
                for c in build.columns:
                    val = c.data[k]
                    valid_k = c.validity[k]
                    cols.append(Column(
                        c.type, jnp.broadcast_to(val, (cap,)),
                        jnp.broadcast_to(valid_k, (cap,)) & probe.row_mask,
                        c.dictionary))
                reps.append(Batch(_plan_schema(node), cols, probe.row_mask))
            yield concat_batches(reps) if len(reps) > 1 else reps[0]

    def _SemiJoinNode(self, node: SemiJoinNode) -> Iterator[Batch]:
        build = self._drain(node.filtering)
        skeys = list(node.source_keys)
        fkeys = list(node.filtering_keys)
        prep = (self._prepare_join_build(build, fkeys,
                                         key_bounds=node.key_bounds)
                if build is not None else None)
        if build is not None:
            from ..ops.join import is_direct_prepared
            _note_join_strategy(
                self.stats, node,
                "direct" if is_direct_prepared(prep) else "sorted",
                node.distribution)
        res_maxk = (self._build_multiplicity(prep)
                    if build is not None and node.residual is not None
                    else None)
        for b in self.run(node.source):
            if build is None:
                if node.negated:
                    yield b
                else:
                    yield Batch(b.schema, b.columns,
                                jnp.zeros_like(b.row_mask))
                continue
            if node.residual is None:
                mask = semi_join_mask_jit(b, build, skeys, fkeys,
                                          node.negated, node.null_aware,
                                          prep)
            else:
                maxk = res_maxk if res_maxk is not None else int(
                    match_count_max_jit(b, build, skeys, fkeys, prep))
                mask = mark_exists_mask(
                    b, build, skeys, fkeys, node.residual, node.negated,
                    bucket_capacity(max(maxk, 1), minimum=1), ex=self)
            yield Batch(b.schema, b.columns, mask)

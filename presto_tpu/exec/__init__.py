from .operators import (  # noqa: F401
    Operator, TableScanOperator, FilterProjectOperator, AggregationOperator,
    OrderByOperator, TopNOperator, LimitOperator, HashBuildOperator,
    LookupJoinOperator, ValuesOperator,
)
from .driver import Driver, Pipeline, run_pipeline  # noqa: F401

from .local import QueryResult, execute_plan  # noqa: F401
from .runner import LocalRunner  # noqa: F401

"""Device-time fair scheduling of concurrent queries.

The role of the reference's TaskExecutor + MultilevelSplitQueue +
PrioritizedSplitRunner (reference presto-main/.../execution/executor/
TaskExecutor.java:79, MultilevelSplitQueue.java:43-44,
PrioritizedSplitRunner.java:43): worker threads time-slice drivers by
cumulative CPU so short queries are not starved behind long scans.

TPU reshape: the contended resource is the one device's dispatch stream,
and the natural quantum is "produce one output batch" (one fused chain
of kernel launches) rather than a 1s wall-clock slice. Each concurrent
query registers a task; before every quantum the driver passes through
``run_quantum``, which grants the device to the eligible task in the
LOWEST level (levels by cumulative device seconds, same thresholds as
the reference: 0/1/10/60/300s), breaking ties by least in-level usage.
A long-running query climbs levels and yields to fresh short queries —
the multilevel feedback queue, without threads owning the device.

Serving plane (PR 8): quanta are first allotted **per resource group**
— stride scheduling over the admitting group's ``schedulingWeight``
(the role of the reference's resource-group CPU-quota split, reshaped
for device time): each billed quantum advances the group's virtual
time by ``billed / weight``, and the waiting task whose group has the
LOWEST virtual time runs next, so under saturation a weight-2 group
receives ~2x the device seconds of a weight-1 group. Starvation-proof
by construction: only running advances virtual time, so a waiting
group's priority can only improve; a group returning from idle is
clamped UP to the floor of the currently-active groups' virtual times
(it competes from now on — it cannot replay its idle period as debt
and monopolize the device). Within one group, tasks keep the
multilevel-feedback order above. Tasks registered without a group
share the default ``""`` group at weight 1.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, List, Optional, TypeVar

from ..obs.metrics import REGISTRY
from ..obs.trace import TRACER

_DEVICE_SECONDS = REGISTRY.counter("scheduler_device_seconds_total")
_QUANTA = REGISTRY.counter("scheduler_quanta_total")
#: quanta weighted by the chips a task occupies: a mesh query's quantum
#: holds EVERY chip in its mesh for the duration, so fair-share
#: accounting (and this observable) bills per chip, not per dispatch
_CHIP_QUANTA = REGISTRY.counter("scheduler_chip_quanta_total")
_WAIT_SECONDS = REGISTRY.histogram("scheduler_wait_seconds")

#: level thresholds in cumulative device seconds (reference
#: MultilevelSplitQueue.LEVEL_THRESHOLD_SECONDS = {0, 1, 10, 60, 300})
LEVEL_THRESHOLDS = (0.0, 1.0, 10.0, 60.0, 300.0)

#: idle GroupShare retention bound (see DeviceScheduler._shares)
_MAX_SHARES = 256


def _service_floor_s() -> float:
    """Modeled per-quantum device-service floor, seconds
    (``PRESTO_TPU_DEVICE_FLOOR_MS``). Zero (the default) is a no-op.

    When set, every quantum holds the device for at least this long —
    a fixed-throughput device model, the same spirit as the object
    spool's modeled RTT/bandwidth. Elasticity benches set it on their
    WORKER processes so per-worker capacity is the bottleneck even on
    a single-core host, where real multi-process compute cannot
    overlap and throughput could never track the worker count."""
    import os
    try:
        return max(0.0, float(
            os.environ.get("PRESTO_TPU_DEVICE_FLOOR_MS", "0") or 0)
            / 1e3)
    except ValueError:
        return 0.0


_SERVICE_FLOOR_S = _service_floor_s()


def device_floor_pad(elapsed_s: float = 0.0) -> None:
    """Pad one fused kernel chain up to the modeled device-service
    floor (no-op unless ``PRESTO_TPU_DEVICE_FLOOR_MS`` is set).

    ``run_quantum`` applies this to each task OUTPUT page, but a source
    task's device work is proportional to the batches it SCANS, and the
    output buffer coalesces those (filters and partial aggregates can
    collapse a whole partition into one output page). Scan paths call
    this per input batch, from inside the owning quantum, so modeled
    per-worker capacity tracks the rows a worker actually processes —
    which is what shrinks when the pool scales out."""
    if _SERVICE_FLOOR_S > 0.0:
        pad = _SERVICE_FLOOR_S - elapsed_s
        if pad > 0.0:
            time.sleep(pad)


R = TypeVar("R")


class GroupShare:
    """One resource group's device-time account (stride scheduling):
    ``vtime`` advances by billed-seconds/weight, so heavier groups
    accrue slower and win eligibility more often. ``name`` is the
    account key (manager-scoped by the serving plane, so two servers'
    same-named groups never share one account); ``label`` is the
    human-facing group path used for the metric series."""

    __slots__ = ("name", "label", "weight", "vtime", "device_seconds",
                 "quanta")

    def __init__(self, name: str, weight: int = 1,
                 label: Optional[str] = None):
        self.name = name
        self.label = label if label is not None else name
        self.weight = max(int(weight), 1)
        self.vtime = 0.0
        self.device_seconds = 0.0
        self.quanta = 0


class TaskHandle:
    def __init__(self, scheduler: "DeviceScheduler", name: str,
                 share: Optional[GroupShare] = None, devices: int = 1):
        self.scheduler = scheduler
        self.name = name
        self.share = share
        #: chips this task's quanta occupy (mesh queries hold the whole
        #: mesh per quantum): billed seconds multiply by it so a
        #: weight-1 tenant cannot buy n chips for the price of one
        self.devices = max(int(devices), 1)
        self.device_seconds = 0.0
        self.quanta = 0
        self.closed = False
        #: query-level abort (worker DELETE /v1/query): a task thread
        #: blocked waiting for its device turn must notice the abort
        #: promptly instead of running one more quantum for a dead query
        self.aborted = threading.Event()
        #: input-stall seconds accrued DURING the current quantum (the
        #: scan prefetcher's consumer waits, exec/scancache.py): credited
        #: back when the quantum closes so device-time fairness bills
        #: compute, not waiting on host-side decode
        self.stall_credit = 0.0

    @property
    def level(self) -> int:
        lv = 0
        for i, t in enumerate(LEVEL_THRESHOLDS):
            if self.device_seconds >= t:
                lv = i
        return lv

    def close(self) -> None:
        self.scheduler.remove(self)


class DeviceScheduler:
    """One per process (one device); tasks round through it."""

    def __init__(self):
        # checked_lock: acquisition edges feed the runtime lock-order
        # validator under pytest (_devtools/lockcheck.py); plain Lock
        # in production
        from .._devtools.lockcheck import checked_lock
        self._lock = checked_lock("taskexec.scheduler")
        self._cv = threading.Condition(self._lock)
        self._tasks: List[TaskHandle] = []
        self._waiting: List[TaskHandle] = []
        self._running: Optional[TaskHandle] = None
        self._running_depth = 0
        #: group key -> GroupShare (the "" default group is created on
        #: first ungrouped task; serving-plane keys are manager-scoped).
        #: Bounded: idle shares beyond _MAX_SHARES evict oldest-first,
        #: so restart-per-tenant / embedded-server churn cannot grow
        #: this dict (or the group_snapshot denominator) forever.
        self._shares: dict = {}
        #: ident of the thread executing the current quantum's fn():
        #: stall credits only attach when the STALLED thread is the one
        #: being billed (a query running outside the scheduler must not
        #: discount another query's quantum)
        self._running_thread: Optional[int] = None

    def task(self, name: str = "", group: str = "",
             weight: int = 1,
             label: Optional[str] = None,
             devices: int = 1) -> TaskHandle:
        with self._lock:
            share = self._shares.get(group)
            if share is None:
                share = self._shares[group] = GroupShare(group, weight,
                                                         label)
            else:
                share.weight = max(int(weight), 1)
            # idle-return clamp: a group with no active task competes
            # from the current floor — its idle period is not device
            # debt it may burn down at everyone else's expense
            active = {t.share for t in self._tasks
                      if t.share is not None and t.share is not share}
            if active and not any(t.share is share for t in self._tasks):
                floor = min(s.vtime for s in active)
                if share.vtime < floor:
                    share.vtime = floor
            h = TaskHandle(self, name, share, devices=devices)
            self._tasks.append(h)
            if len(self._shares) > _MAX_SHARES:
                live = {t.share for t in self._tasks
                        if t.share is not None}
                for key in list(self._shares):
                    if len(self._shares) <= _MAX_SHARES:
                        break
                    if self._shares[key] not in live:
                        del self._shares[key]
        return h

    def group_shares(self) -> dict:
        """Per-group ledger snapshot (system.runtime.resource_groups)."""
        with self._lock:
            return {name: {"weight": s.weight, "vtime": s.vtime,
                           "device_seconds": s.device_seconds,
                           "quanta": s.quanta}
                    for name, s in self._shares.items()}

    def remove(self, handle: TaskHandle) -> None:
        with self._cv:
            handle.closed = True
            if handle in self._tasks:
                self._tasks.remove(handle)
            self._cv.notify_all()

    @staticmethod
    def _wait_key(t: TaskHandle):
        """Group virtual time first (stride fairness across groups),
        then the multilevel-feedback order within the group."""
        vtime = t.share.vtime if t.share is not None else 0.0
        return (vtime, t.level, t.device_seconds)

    def _eligible(self, handle: TaskHandle) -> bool:
        if self._running is handle:
            return True       # re-entrant: tasks of one query (pipeline
            # stages feeding each other) must not serialize against
            # themselves — only against OTHER queries
        if self._running is not None:
            return False
        best = min(self._waiting, key=self._wait_key)
        return best is handle

    def run_quantum(self, handle: Optional[TaskHandle],
                    fn: Callable[[], R]) -> R:
        """Run ``fn`` (one batch's worth of device dispatches) when it is
        this task's turn; account its wall time as device time."""
        if handle is None:
            return fn()
        if handle.aborted.is_set():
            from ..errors import QueryCancelledError
            raise QueryCancelledError("query aborted")
        t_wait = time.perf_counter()
        with self._cv:
            self._waiting.append(handle)
            try:
                while not self._eligible(handle):
                    if handle.aborted.is_set():
                        from ..errors import QueryCancelledError
                        raise QueryCancelledError("query aborted")
                    self._cv.wait(timeout=1.0)
            finally:
                self._waiting.remove(handle)
            self._running = handle
            self._running_thread = threading.get_ident()
            self._running_depth += 1
        t0 = time.perf_counter()
        _WAIT_SECONDS.observe(t0 - t_wait)
        span = (TRACER.span("quantum", task=handle.name,
                            level=handle.level)
                if TRACER.enabled else None)
        try:
            result = fn()
            # fixed-throughput device model: pad the quantum to the
            # floor while HOLDING the device, so capacity is
            # per-worker and additive across workers
            device_floor_pad(time.perf_counter() - t0)
            return result
        finally:
            dt = time.perf_counter() - t0
            if span is not None:
                span.finish()
            _QUANTA.inc()
            with self._cv:
                # input-stall credit (note_stall): time this quantum
                # spent blocked on the scan prefetcher is not device
                # time — billing it would climb an input-bound query up
                # the levels for compute it never dispatched
                credit = min(handle.stall_credit, dt)
                handle.stall_credit = 0.0
                # per-chip billing: a quantum on an n-device mesh
                # consumed n chip-seconds of the fleet per wall second
                billed = (dt - credit) * handle.devices
                _DEVICE_SECONDS.inc(billed)
                _CHIP_QUANTA.inc(handle.devices)
                handle.device_seconds += billed
                handle.quanta += 1
                if handle.share is not None:
                    # stride accounting: billed seconds advance the
                    # group's virtual time inversely to its weight
                    share = handle.share
                    share.vtime += billed / share.weight
                    share.device_seconds += billed
                    share.quanta += 1
                    if share.label:
                        REGISTRY.counter(
                            "resource_group_device_seconds_total."
                            f"{share.label}").inc(billed)
                self._running_depth -= 1
                if self._running_depth == 0:
                    self._running = None
                    self._running_thread = None
                self._cv.notify_all()

    @contextmanager
    def stalled(self, handle: Optional[TaskHandle]):
        """Release the device for the duration of a blocking INPUT
        wait inside a quantum (an exchange consumer parked on remote
        pages), re-acquiring through normal eligibility on exit.

        ``note_stall`` credits the TIME; this releases the DEVICE.
        Without it a consumer blocked on another worker's producer
        holds this worker's device, and two workers whose consumers
        wait on each other's starved producers deadlock the fleet —
        the multi-process cluster's version of the classic
        quantum-holder-waits-on-queued-producer cycle (single-process
        clusters never see it: all workers share one scheduler and a
        query's tasks share one re-entrant handle)."""
        ident = threading.get_ident()
        with self._cv:
            # the calling thread is inside run_quantum for this handle
            # (it owns one nesting level), so giving that level back is
            # safe even when a sibling thread of the same query is the
            # recorded runner
            held = (handle is not None and self._running is handle
                    and self._running_depth > 0)
            if held:
                self._running_depth -= 1
                if self._running_depth == 0:
                    self._running = None
                    self._running_thread = None
                REGISTRY.counter("device_stall_release_total").inc()
                self._cv.notify_all()
        try:
            yield
        finally:
            if held:
                # re-acquire as soon as the device frees: this is the
                # CONTINUATION of a quantum already granted through
                # fair eligibility, not a new one — rejoining the
                # fair queue here would bill one full queue rotation
                # per input page, quantizing exchange-bound queries to
                # the whole cluster's quantum length. Abort is NOT an
                # escape hatch: the nesting level must be restored so
                # the enclosing run_quantum's bookkeeping stays
                # balanced; the body's own cancellation check raises
                # right after.
                with self._cv:
                    while not (self._running is None
                               or self._running is handle):
                        self._cv.wait(timeout=1.0)
                    self._running = handle
                    self._running_thread = ident
                    self._running_depth += 1

    def note_stall(self, seconds: float) -> None:
        """Record input-stall time (the scan pipeline's consumer waited
        on a prefetch queue) against the currently-running quantum —
        only when the caller IS that quantum's thread, so a query
        stalling outside the scheduler (init plans, fair_scheduling off)
        never discounts another query's bill."""
        with self._cv:
            if self._running is not None \
                    and self._running_thread == threading.get_ident():
                self._running.stall_credit += seconds


#: process-wide scheduler (one real device per process)
GLOBAL = DeviceScheduler()

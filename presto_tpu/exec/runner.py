"""LocalRunner: SQL text -> results in one process.

Conceptual parity with the reference's LocalQueryRunner (reference
presto-main/.../testing/LocalQueryRunner.java:210): the full
parse -> analyze/plan -> optimize -> execute path with in-process
connectors and no network — ring 2 of the test strategy (SURVEY.md §4).
Session statements (SET/SHOW) and EXPLAIN are served directly, like the
reference's DataDefinitionTask dispatch (reference execution/
SetSessionTask.java etc.).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import types as T
from ..batch import Batch
from ..connectors.memory import MemoryConnector
from ..connectors.spi import CatalogManager, TableHandle
from ..connectors.tpch import TpchConnector
from ..obs.metrics import REGISTRY, attach_event_listeners
from ..obs.trace import TRACER
from ..sql import ast as A
from ..sql.ast import count_parameters, substitute_parameters
from ..sql.parser import parse_statement
from ..planner.optimizer import optimize
from ..planner.planner import LogicalPlan, Session, plan_query
from ..planner.printer import print_plan
from .local import QueryResult, execute_plan


class LocalRunner:
    def __init__(self, catalogs: Optional[CatalogManager] = None,
                 catalog: str = "tpch", schema: str = "default",
                 tpch_sf: float = 0.01, rows_per_batch: int = 1 << 17):
        if catalogs is None:
            from ..connectors.tpcds import TpcdsConnector
            catalogs = CatalogManager()
            catalogs.register("tpch", TpchConnector(sf=tpch_sf))
            catalogs.register("tpcds", TpcdsConnector(sf=tpch_sf))
            catalogs.register("memory", MemoryConnector())
        if "system" not in catalogs.names():
            from ..connectors.system import SystemConnector
            catalogs.register("system", SystemConnector(catalogs))
        self.session = Session(catalogs=catalogs, catalog=catalog,
                               schema=schema)
        from ..transaction import TransactionManager
        from ..events import EventListenerManager
        from ..server.security import AccessControl
        self.transactions = TransactionManager()
        self.events = EventListenerManager()
        # metrics sink: query/split completion events feed the
        # process-wide registry (system.runtime.metrics)
        attach_event_listeners(self.events)
        # history sink: every completed query leaves one final record
        # in the process-wide store (system.runtime.completed_queries)
        from ..obs.history import attach_history
        attach_history(self.events)
        self.access_control = AccessControl()    # allow-all until rules set
        from ..server.security import RoleManager
        self.roles = RoleManager()               # enforce=False by default
        self.rows_per_batch = rows_per_batch
        self.query_log = catalogs.get("system").query_log
        self._query_seq = 0
        #: query id -> live StatsCollector (the /v1/query/{id} surface)
        self.live_stats: Dict[str, object] = {}
        # checked_lock: the cluster plane acquires this lock too (query
        # registration/log), so its edges belong in the runtime
        # lock-order graph (_devtools/lockcheck.py)
        from .._devtools.lockcheck import checked_lock
        self._state_lock = checked_lock("runner.state")

    # -- public API -----------------------------------------------------------
    def execute(self, sql: str,
                properties: Optional[Dict[str, object]] = None,
                user: str = "", cancel_event=None,
                serving=None) -> QueryResult:
        """Run one statement. ``properties`` overlays per-query session
        properties without mutating the shared session (needed for
        concurrent queries under resource groups; the reference builds a
        per-query Session the same way, Session.java +
        QuerySessionSupplier). ``user`` scopes access-control checks and
        query events. ``serving`` is the admitted query's resource-group
        context (serving/groups.QueryServingContext): group memory
        accounting + weighted device-scheduler share."""
        import time as _time
        from ..connectors.system import QueryLogEntry
        from ..events import completed_event
        from ..exec.stats import StatsCollector
        from ..events import SplitCompletedEvent
        from ..serving.plancache import parse_cached
        # repeated-statement fast path, step 1: identical SQL text
        # reuses the parsed AST (frozen dataclasses)
        stmt = parse_cached(sql)
        with self._state_lock:
            self._query_seq += 1
            qid = f"q_{self._query_seq:06d}"
            entry = QueryLogEntry(qid, "RUNNING", sql.strip(), 0.0,
                                  user=user, create_time=_time.time())
            self.query_log.append(entry)
            # live per-query stats (wall/batches per node + split events),
            # served by GET /v1/query/{id} while the query runs
            # (reference server/QueryResource.java live stage stats)
            stats = StatsCollector(count_rows=False)
            self.live_stats[qid] = stats
            if len(self.live_stats) > 200:
                running = {e.query_id for e in self.query_log
                           if e.state == "RUNNING"}
                for old in list(self.live_stats)[:-100]:
                    if old not in running:   # keep live queries visible
                        del self.live_stats[old]
        t0 = _time.perf_counter()
        c0 = _time.process_time()
        error: Optional[str] = None
        error_code: Optional[str] = None
        rows_out: Optional[int] = None
        trace_id = None
        REGISTRY.counter("queries_started_total").inc()
        try:
            with TRACER.span("query", query_id=qid, user=user) as qspan:
                trace_id = getattr(qspan, "trace_id", None)
                out = self._execute_stmt(stmt, properties, user,
                                         cancel_event=cancel_event,
                                         stats=stats, serving=serving)
            rows_out = len(out.rows)
            entry.state = "FINISHED"
            return out
        except Exception as e:
            entry.state = "FAILED"
            error = str(e)
            error_code = getattr(e, "name", type(e).__name__)
            raise
        finally:
            entry.elapsed_ms = (_time.perf_counter() - t0) * 1e3
            entry.error = error
            with self._state_lock:
                if len(self.query_log) > 1000:
                    del self.query_log[:-500]
            self._feed_metrics(stats)
            for s in stats.splits:
                self.events.split_completed(SplitCompletedEvent(
                    qid, s["table"], s["split"], s["wallMs"],
                    s["batches"]))
            cpu_ms = (_time.process_time() - c0) * 1e3
            self.events.query_completed(completed_event(
                qid, sql.strip(), user, entry.state, t0, error,
                history=self._history_record(
                    entry, stats, user, cpu_ms, rows_out, error_code,
                    trace_id)))
            from ..obs.log import LOG
            if LOG.enabled:
                LOG.log("query_completed", query_id=qid,
                        state=entry.state, user=user,
                        elapsed_ms=round(entry.elapsed_ms, 3),
                        error=error)

    def _feed_metrics(self, stats) -> None:
        """Fold one query's per-node stats and memory-pool stats into the
        process-wide registry (batches/rows per operator kind, spill
        bytes, pool high-water mark)."""
        for node, st in list(stats.by_node.items()):
            kind = type(node).__name__.replace("Node", "").lower()
            REGISTRY.counter(f"operator_batches_total.{kind}").inc(
                st.batches)
            REGISTRY.counter(f"operator_seconds_total.{kind}").inc(
                st.wall_s)
            if st.rows:
                REGISTRY.counter(f"operator_rows_total.{kind}").inc(
                    st.rows)
        # memory_pool_peak_bytes is fed at reservation time (memory.py
        # _POOL_PEAK) — the pool, not the query, owns that gauge

    def _history_record(self, entry, stats, user: str, cpu_ms: float,
                        rows_out: Optional[int],
                        error_code: Optional[str],
                        trace_id) -> Dict[str, object]:
        """Final per-query record for the history store: plan summary
        + per-operator rows/batches/wall from the StatsCollector, peak
        memory from the pool, and (tracer on) plan/device-sync seconds
        from this query's spans."""
        by_kind: Dict[str, Dict[str, float]] = {}
        for node, st in list(stats.by_node.items()):
            kind = type(node).__name__.replace("Node", "")
            agg = by_kind.setdefault(
                kind, {"rows": 0, "batches": 0, "wall_ms": 0.0,
                       "device_time_s": 0.0, "flops": 0.0,
                       "hbm_bytes": 0.0})
            agg["rows"] += st.rows
            agg["batches"] += st.batches
            agg["wall_ms"] += st.wall_s * 1e3
            # device truth (profile mode): seconds/FLOPs/HBM bytes the
            # profiler attributed to this operator's jit dispatches —
            # zeros on the unprofiled path
            dev = stats.device_for(node) \
                if hasattr(stats, "device_for") else None
            if dev is not None:
                agg["device_time_s"] += dev["device_time_s"]
                agg["flops"] += dev["flops"]
                agg["hbm_bytes"] += dev["hbm_bytes"]
        # no "bytes" key: the local stats collector doesn't measure
        # operator output bytes (cluster records carry per-task
        # bytesOut); rows are live only in analyze mode — counting
        # them on the normal path would cost a device sync per batch
        operators = [{"operator": k, "rows": int(v["rows"]),
                      "batches": int(v["batches"]),
                      "wall_ms": round(v["wall_ms"], 3),
                      "device_time_s": round(v["device_time_s"], 6),
                      "flops": v["flops"],
                      "hbm_bytes": int(v["hbm_bytes"])}
                     for k, v in by_kind.items()]
        pool_stats = getattr(self.session, "last_memory_stats", None)
        planning_ms = device_sync_ms = 0.0
        if TRACER.enabled and trace_id is not None:
            for s in TRACER.export(trace_id):
                dur = (float(s.get("end", 0.0))
                       - float(s.get("start", 0.0))) * 1e3
                if s.get("name") == "plan":
                    planning_ms += dur
                elif s.get("name") == "device-sync":
                    device_sync_ms += dur
        record = {
            "query_id": entry.query_id, "query": entry.query,
            "user": user, "state": entry.state, "error": entry.error,
            "error_code": error_code, "create_time": entry.create_time,
            "elapsed_ms": round(entry.elapsed_ms, 3),
            "cpu_ms": round(cpu_ms, 3),
            "device_sync_ms": round(device_sync_ms, 3),
            "planning_ms": round(planning_ms, 3),
            "peak_memory_bytes": int(
                getattr(pool_stats, "peak_bytes", 0) or 0),
            "rows": rows_out, "mode": "local",
            "plan_summary": " -> ".join(by_kind),
            "operators": operators,
        }
        # mesh-path queries carry the flight recorder's attribution
        # summary (obs/flight.py) into the persistent history
        fl = getattr(stats, "mesh_flight", None)
        if fl is not None:
            from ..obs.flight import history_fields
            # re-stamp the runner's query id (the tracer-off fallback
            # was a synthetic mesh_* id) so mesh_rounds joins against
            # completed_queries
            fl.query_id = entry.query_id
            if fl.attribution is not None:
                fl.attribution["query_id"] = entry.query_id
            record.update(history_fields(fl.attribution))
        return record

    def plan(self, sql: str, optimized: bool = True) -> LogicalPlan:
        stmt = parse_statement(sql)
        if not isinstance(stmt, A.Query):
            raise ValueError("plan() takes a SELECT query")
        plan = plan_query(stmt, self.session)
        return optimize(plan, self.session) if optimized else plan

    # -- statement dispatch ---------------------------------------------------
    def _execute_stmt(self, stmt: A.Node,
                      properties: Optional[Dict[str, object]] = None,
                      user: str = "", cancel_event=None,
                      stats=None, serving=None) -> QueryResult:
        import dataclasses as _dc
        session = self.session
        secured = bool(self.access_control.catalog_rules)
        if properties or secured or serving is not None:
            catalogs = session.catalogs
            if secured:
                from ..server.security import SecuredCatalogs
                catalogs = SecuredCatalogs(catalogs, user,
                                           self.access_control)
            session = _dc.replace(
                session, catalogs=catalogs, serving=serving,
                properties={**session.properties, **(properties or {})})
        if isinstance(stmt, A.Query):
            # repeated-statement fast path, step 2: a fingerprint hit in
            # the compiled-plan cache (serving/plancache.py) skips
            # plan_query + optimize entirely — the plan's jitted
            # executables are already warm in ops/jitcache. Under
            # plan_template_cache the fingerprint is PARAMETER-GENERIC
            # (serving/template.py): the statement's literals
            # hole-punch out of the key and bind at execution as traced
            # scalars, so an EXECUTE fleet shares one plan + one warm
            # executable set across bindings.
            from ..planner.planner import bool_property
            from ..serving.plancache import bound_fingerprint, cached_plan
            sec = secured or self.roles.enforce
            use_template = bool_property(session, "plan_template_cache",
                                         False)
            use_results = bool_property(session, "result_cache", False)
            bindings = bound_key = None
            with TRACER.span("plan"):
                if use_template:
                    from ..serving.template import template_plan
                    plan, bindings, bound_key = template_plan(
                        stmt, session, user=user, secured=sec)
                else:
                    plan = cached_plan(stmt, session, user=user,
                                       secured=sec)
            if secured:
                # a cache hit skips planning — where SecuredCatalogs
                # enforces — so re-check catalog access on the plan's
                # scans (a revoked grant must bite on warm plans too)
                self._check_catalog_access(plan, user)
            if self.roles.enforce:
                self._check_select_privileges(plan, user)
            if bindings is not None:
                # per-query overlay: the executor opens the binding
                # scope from this field (never mutate the shared plan)
                session = _dc.replace(session, param_bindings=bindings)
            rc_token = None
            try:
                if use_results:
                    from ..serving import resultcache as RC
                    if bound_key is None:
                        bound_key = bound_fingerprint(
                            stmt, session, user=user, secured=sec)
                    # deps + epoch stamp BEFORE running: a write
                    # landing mid-execution vetoes the insert (the
                    # plan-cache TOCTOU contract)
                    served, rc_token = RC.begin(
                        bound_key, plan, session, self.rows_per_batch,
                        cancel_event=cancel_event, stats=stats)
                    if served is not None:
                        return served
                out = execute_plan(plan, session, self.rows_per_batch,
                                   stats=stats,
                                   cancel_event=cancel_event)
                if rc_token is not None:
                    from ..serving import resultcache as RC
                    RC.commit(rc_token, session, out)
                return out
            finally:
                if session is not self.session:
                    # the executor stamped its memory stats on the
                    # per-query overlay; surface them on the shared
                    # session like property-less queries do
                    self.session.last_memory_stats = \
                        session.last_memory_stats
        if isinstance(stmt, A.Explain):
            if not isinstance(stmt.statement, A.Query):
                raise ValueError("EXPLAIN requires a query")
            import time as _time
            if stmt.analyze and (stmt.type != "logical"
                                 or stmt.format != "text"):
                raise ValueError(
                    "EXPLAIN ANALYZE does not take TYPE/FORMAT options")
            t0 = _time.perf_counter()
            plan = optimize(plan_query(stmt.statement, session), session)
            if stmt.type == "validate":
                return QueryResult(["Valid"], [T.BOOLEAN], [(True,)])
            if stmt.type == "io":
                import json as _json

                from ..planner.printer import plan_io
                doc = _json.dumps(plan_io(plan), indent=2)
                return QueryResult(["Query Plan"], [T.VARCHAR],
                                   [(line,) for line in doc.split("\n")])
            stats = None
            trace_spans = None
            if stmt.analyze:
                # EXPLAIN ANALYZE: run the query with per-operator stats,
                # draining batches without materializing client rows
                # (reference operator/ExplainAnalyzeOperator.java)
                from .stats import StatsCollector
                stats = StatsCollector(count_rows=True)
                stats.planning_s = _time.perf_counter() - t0
                t1 = _time.perf_counter()
                with TRACER.span("explain-analyze") as sp:
                    execute_plan(plan, session, self.rows_per_batch,
                                 stats=stats, collect_rows=False,
                                 cancel_event=cancel_event)
                stats.total_wall_s = _time.perf_counter() - t1
                tid = getattr(sp, "trace_id", None)
                if TRACER.enabled and tid is not None:
                    trace_spans = TRACER.export(tid)
                from ..planner.planner import bool_property
                if bool_property(session, "result_cache", False):
                    # EXPLAIN ANALYZE always executes (that's the
                    # point) — report whether a resident entry would
                    # have served this statement. Same key rule as the
                    # execution path (bound_fingerprint) or the probe
                    # would silently probe a key nothing stores under.
                    from ..serving import resultcache as RC
                    from ..serving.plancache import bound_fingerprint
                    key = bound_fingerprint(
                        stmt.statement, session, user=user,
                        secured=secured or self.roles.enforce)
                    stats.result_cache_probe = RC.RESULTS.probe(key)
                    stats.result_cache_stats = RC.RESULTS.stats()
            if stmt.type == "distributed":
                if stmt.format != "text":
                    raise ValueError(
                        "EXPLAIN (TYPE DISTRIBUTED) only supports "
                        "FORMAT TEXT")
                from ..planner.printer import print_distributed_plan
                text = print_distributed_plan(plan)
            elif stmt.format == "json":
                import json as _json

                from ..planner.printer import plan_json
                text = _json.dumps(plan_json(plan), indent=2)
            elif stmt.format == "graphviz":
                from ..planner.printer import plan_graphviz
                text = plan_graphviz(plan)
            else:
                text = print_plan(plan, stats)
                if trace_spans:
                    from ..planner.printer import format_trace_summary
                    text += "\n" + format_trace_summary(trace_spans)
                if stats is not None:
                    from ..planner.printer import (
                        format_cost_verdict, format_executables_summary,
                        format_mesh_rounds, format_result_cache_summary,
                        format_scan_cache_summary, format_skew_summary,
                    )
                    skew = format_skew_summary(stats)
                    if skew:
                        text += "\n" + skew
                    mesh_sec = format_mesh_rounds(stats)
                    if mesh_sec:
                        text += "\n" + mesh_sec
                    sc = format_scan_cache_summary(stats)
                    if sc:
                        text += "\n" + sc
                    rc = format_result_cache_summary(stats)
                    if rc:
                        text += "\n" + rc
                    exes = format_executables_summary(stats)
                    if exes:
                        text += "\n" + exes
                    verdict = format_cost_verdict(stats)
                    if verdict:
                        text += "\n" + verdict
            return QueryResult(["Query Plan"], [T.VARCHAR],
                               [(line,) for line in text.split("\n")])
        if isinstance(stmt, A.ShowCatalogs):
            return QueryResult(["Catalog"], [T.VARCHAR],
                               [(c,) for c in session.catalogs.names()])
        if isinstance(stmt, A.ShowTables):
            conn = session.catalogs.get(session.catalog)
            names = list(conn.metadata.list_tables())
            names += [v[2] for v in self.session.views
                      if v[0] == session.catalog
                      and v[1] == session.schema]
            return QueryResult(
                ["Table"], [T.VARCHAR], [(t,) for t in sorted(names)])
        if isinstance(stmt, A.ShowColumns):
            name = stmt.table
            catalog = self.session.catalog if len(name) < 3 else name[-3]
            schema = self.session.schema if len(name) < 2 else name[-2]
            view = self.session.views.get((catalog, schema, name[-1]))
            if view is not None:
                plan = plan_query(view, session)
                return QueryResult(
                    ["Column", "Type"], [T.VARCHAR, T.VARCHAR],
                    [(f.name, f.type.display())
                     for f in plan.root.fields])
            conn = session.catalogs.get(catalog)
            ts = conn.metadata.table_schema(
                TableHandle(catalog, schema, name[-1]))
            return QueryResult(
                ["Column", "Type"], [T.VARCHAR, T.VARCHAR],
                [(f.name, f.type.display()) for f in ts.fields])
        if isinstance(stmt, A.ShowSession):
            return QueryResult(
                ["Name", "Value"], [T.VARCHAR, T.VARCHAR],
                [(k, str(v)) for k, v in
                 sorted(self.session.properties.items())])
        if isinstance(stmt, A.SetSession):
            # validate against the declared registry (config.py): an
            # unknown or type-mismatched property fails the statement
            # instead of silently latching a string no read site will
            # ever consult
            from ..config import validate_session_property
            value = validate_session_property(
                stmt.name, _literal_value(stmt.value))
            self.session.properties[stmt.name] = value
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.ResetSession):
            self.session.properties.pop(stmt.name, None)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.CreateRole):
            self.roles.create_role(stmt.name, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.DropRole):
            self.roles.drop_role(stmt.name, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.GrantRoles):
            self.roles.grant_roles(stmt.roles, stmt.grantees, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.RevokeRoles):
            self.roles.revoke_roles(stmt.roles, stmt.grantees, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.GrantPrivileges):
            cat, _, tab = self._object_key(stmt.table)
            self.roles.grant_table(stmt.privileges, cat, tab,
                                   stmt.grantee, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.RevokePrivileges):
            cat, _, tab = self._object_key(stmt.table)
            self.roles.revoke_table(stmt.privileges, cat, tab,
                                    stmt.grantee, user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.SetRole):
            # session-scoped role selection; ALL/NONE accepted for
            # compatibility (enforcement consults all granted roles)
            self.session.properties["role"] = stmt.role
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.ShowRoles):
            return QueryResult(["Role"], [T.VARCHAR],
                               [(r,) for r in self.roles.list_roles()])
        if isinstance(stmt, A.ShowGrants):
            tbl = None
            if stmt.table:
                cat, _, tab = self._object_key(stmt.table)
                tbl = (cat, tab)
            return QueryResult(
                ["Grantee", "Catalog", "Table", "Privilege"],
                [T.VARCHAR] * 4,
                self.roles.list_grants(tbl))
        if isinstance(stmt, A.StartTransaction):
            tx_id = self.transactions.begin(stmt.isolation,
                                            stmt.read_only, user=user)
            return QueryResult(["result"], [T.VARCHAR], [(tx_id,)])
        if isinstance(stmt, A.Commit):
            self.transactions.commit(user=user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.Rollback):
            self.transactions.rollback(user=user)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.CreateTableAsSelect):
            return self._ctas(stmt, session, user,
                              cancel_event=cancel_event)
        if isinstance(stmt, A.InsertInto):
            return self._insert(stmt, session, user,
                                cancel_event=cancel_event)
        if isinstance(stmt, A.DropTable):
            conn, table = self._writable(stmt.name, user)
            conn.drop_table(table, if_exists=stmt.if_exists)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.CreateView):
            key = self._object_key(stmt.name)
            if key in self.session.views and not stmt.or_replace:
                raise ValueError(f"view {'.'.join(key)} already exists")
            try:
                existing = session.catalogs.get(
                    key[0]).metadata.list_tables()
            except Exception:
                existing = ()
            if key[2] in existing:
                raise ValueError(
                    f"table {'.'.join(key)} already exists (a view "
                    "cannot shadow a table)")
            # validate now: a broken view should fail CREATE, not SELECT
            plan_query(stmt.query, session)
            self.session.views[key] = stmt.query
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.DropView):
            key = self._object_key(stmt.name)
            if key not in self.session.views:
                if stmt.if_exists:
                    return QueryResult(["result"], [T.BOOLEAN], [(True,)])
                raise ValueError(f"view {'.'.join(key)} does not exist")
            del self.session.views[key]
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.Prepare):
            if isinstance(stmt.statement, (A.Prepare, A.ExecuteStmt,
                                           A.Deallocate)):
                raise ValueError(
                    "cannot prepare PREPARE/EXECUTE/DEALLOCATE statements")
            self.session.prepared[stmt.name] = stmt.statement
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.Deallocate):
            if self.session.prepared.pop(stmt.name, None) is None:
                raise ValueError(
                    f"prepared statement {stmt.name!r} not found")
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.ExecuteStmt):
            prepared = self.session.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(
                    f"prepared statement {stmt.name!r} not found")
            want = count_parameters(prepared)
            if len(stmt.args) != want:
                raise ValueError(
                    f"Incorrect number of parameters: expected {want} "
                    f"but found {len(stmt.args)}")
            bound = substitute_parameters(prepared, list(stmt.args))
            return self._execute_stmt(bound, properties, user,
                                      cancel_event=cancel_event,
                                      stats=stats, serving=serving)
        if isinstance(stmt, A.DescribeOutput):
            prepared = self.session.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(
                    f"prepared statement {stmt.name!r} not found")
            if not isinstance(prepared, A.Query):
                return QueryResult(["Column Name", "Type"],
                                   [T.VARCHAR, T.VARCHAR], [])
            # bind NULL for parameters: output shape doesn't depend on them
            n_params = count_parameters(prepared)
            bound = substitute_parameters(
                prepared, [A.NullLiteral()] * n_params)
            plan = optimize(plan_query(bound, session), session)
            root = plan.root
            return QueryResult(
                ["Column Name", "Type"], [T.VARCHAR, T.VARCHAR],
                [(f.name, f.type.display()) for f in root.fields])
        if isinstance(stmt, A.DescribeInput):
            prepared = self.session.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(
                    f"prepared statement {stmt.name!r} not found")
            n = count_parameters(prepared)
            return QueryResult(["Position", "Type"], [T.BIGINT, T.VARCHAR],
                               [(i, "unknown") for i in range(n)])
        raise NotImplementedError(
            f"statement {type(stmt).__name__} is not supported yet")

    def _object_key(self, name) -> tuple:
        catalog = self.session.catalog if len(name) < 3 else name[-3]
        schema = self.session.schema if len(name) < 2 else name[-2]
        return (catalog, schema, name[-1])

    def _check_catalog_access(self, plan: LogicalPlan,
                              user: str) -> None:
        """Catalog-level access control over a plan's scans — the check
        SecuredCatalogs performs at plan time, repeated here so plans
        served from the cache (planning skipped) stay enforced."""
        from ..planner.plan import TableScanNode

        def walk(n):
            if isinstance(n, TableScanNode):
                self.access_control.check_can_access_catalog(
                    user, n.catalog)
            for c in n.children:
                walk(c)
        for p in [plan.root] + list(plan.init_plans):
            walk(p)

    def _check_select_privileges(self, plan: LogicalPlan,
                                 user: str) -> None:
        """SQL-standard enforcement: every scanned table needs SELECT
        for the user (directly or via a role) when the role manager is
        enforcing (reference security/AccessControlManager.checkCanSelectFromColumns)."""
        from ..planner.plan import TableScanNode

        def walk(n):
            if isinstance(n, TableScanNode):
                self.roles.check_table_privilege(
                    user, n.catalog, n.table.table, "SELECT")
            for c in n.children:
                walk(c)
        for p in [plan.root] + list(plan.init_plans):
            walk(p)

    # -- write path (reference TableWriterOperator + finishInsert) ----------
    def _writable(self, name, user: str = ""):
        from ..planner.planner import _schema_exists
        catalog = self.session.catalog if len(name) < 3 else name[-3]
        if len(name) == 2 and self.session.catalogs.exists(name[0]) \
                and not _schema_exists(self.session, name[0]):
            # two-part name whose qualifier names a mounted catalog (and
            # no session-catalog schema shadows it): catalog.table with
            # the default schema (matches the read path's resolution in
            # planner.plan_table, so the same name reads and writes one
            # table)
            catalog = name[0]
        self.access_control.check_can_access_catalog(user, catalog)
        if self.roles.enforce:
            self.roles.check_table_privilege(user, catalog, name[-1],
                                             "INSERT")
        conn = self.session.catalogs.get(catalog)
        if not hasattr(conn, "create_table"):
            raise ValueError(f"catalog {catalog!r} is not writable")
        # inside an explicit transaction: snapshot before the first write
        # so ROLLBACK can restore (auto-commit outside one)
        self.transactions.touch_for_write(catalog, conn, user=user)
        return conn, name[-1]

    def _run_to_batches(self, query: A.Query, session=None,
                        cancel_event=None):
        from ..batch import Schema
        from .local import _Executor, run_init_plans
        session = session or self.session
        plan = optimize(plan_query(query, session), session)
        ex = _Executor(session, self.rows_per_batch)
        ex.cancel_event = cancel_event
        run_init_plans(ex, plan)
        root = plan.root
        schema = Schema([(f.name, f.type) for f in root.fields])
        # drain and error-check BEFORE the caller appends to the target:
        # a failing INSERT ... SELECT must not persist partial rows
        # (reference TableFinishOperator commits only on success)
        out = list(ex.run(root.child))
        ex.check_errors()
        return schema, iter(out)

    def _ctas(self, stmt: A.CreateTableAsSelect, session=None,
              user: str = "", cancel_event=None) -> QueryResult:
        conn, table = self._writable(stmt.name, user)
        # the source query plans against the SECURED per-query session:
        # INSERT ... SELECT must not read catalogs the user cannot SELECT
        schema, batches = self._run_to_batches(stmt.query, session,
                                               cancel_event=cancel_event)
        if table in conn.tables and stmt.if_not_exists:
            return QueryResult(["rows"], [T.BIGINT], [(0,)])
        props = dict(getattr(stmt, "properties", ()) or ())
        part_by = props.pop("partitioned_by", ())
        if props:
            raise ValueError(
                f"unknown table properties: {sorted(props)}")
        if part_by:
            conn.create_table(table, schema,
                              if_not_exists=stmt.if_not_exists,
                              partitioned_by=list(part_by))
        else:
            conn.create_table(table, schema,
                              if_not_exists=stmt.if_not_exists)
        n = 0
        for b in batches:
            n += conn.append(table, Batch(schema, b.columns, b.row_mask))
        return QueryResult(["rows"], [T.BIGINT], [(n,)])

    def _insert(self, stmt: A.InsertInto, session=None,
                user: str = "", cancel_event=None) -> QueryResult:
        conn, table = self._writable(stmt.name, user)
        schema, batches = self._run_to_batches(stmt.query, session,
                                               cancel_event=cancel_event)
        n = 0
        for b in batches:
            n += conn.append(table, Batch(schema, b.columns, b.row_mask))
        return QueryResult(["rows"], [T.BIGINT], [(n,)])


def _literal_value(e: A.Expression):
    if isinstance(e, A.StringLiteral):
        return e.value
    if isinstance(e, A.LongLiteral):
        return e.value
    if isinstance(e, A.DoubleLiteral):
        return e.value
    if isinstance(e, A.DecimalLiteral):
        return e.value
    if isinstance(e, A.BooleanLiteral):
        return e.value
    raise ValueError("session value must be a literal")

"""LocalRunner: SQL text -> results in one process.

Conceptual parity with the reference's LocalQueryRunner (reference
presto-main/.../testing/LocalQueryRunner.java:210): the full
parse -> analyze/plan -> optimize -> execute path with in-process
connectors and no network — ring 2 of the test strategy (SURVEY.md §4).
Session statements (SET/SHOW) and EXPLAIN are served directly, like the
reference's DataDefinitionTask dispatch (reference execution/
SetSessionTask.java etc.).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import types as T
from ..batch import Batch
from ..connectors.memory import MemoryConnector
from ..connectors.spi import CatalogManager, TableHandle
from ..connectors.tpch import TpchConnector
from ..sql import ast as A
from ..sql.parser import parse_statement
from ..planner.optimizer import optimize
from ..planner.planner import LogicalPlan, Session, plan_query
from ..planner.printer import print_plan
from .local import QueryResult, execute_plan


class LocalRunner:
    def __init__(self, catalogs: Optional[CatalogManager] = None,
                 catalog: str = "tpch", schema: str = "default",
                 tpch_sf: float = 0.01, rows_per_batch: int = 1 << 17):
        if catalogs is None:
            from ..connectors.tpcds import TpcdsConnector
            catalogs = CatalogManager()
            catalogs.register("tpch", TpchConnector(sf=tpch_sf))
            catalogs.register("tpcds", TpcdsConnector(sf=tpch_sf))
            catalogs.register("memory", MemoryConnector())
        if "system" not in catalogs.names():
            from ..connectors.system import SystemConnector
            catalogs.register("system", SystemConnector(catalogs))
        self.session = Session(catalogs=catalogs, catalog=catalog,
                               schema=schema)
        self.rows_per_batch = rows_per_batch
        self.query_log = catalogs.get("system").query_log
        self._query_seq = 0

    # -- public API -----------------------------------------------------------
    def execute(self, sql: str) -> QueryResult:
        import time as _time
        from ..connectors.system import QueryLogEntry
        stmt = parse_statement(sql)
        self._query_seq += 1
        qid = f"q_{self._query_seq:06d}"
        entry = QueryLogEntry(qid, "RUNNING", sql.strip(), 0.0)
        self.query_log.append(entry)
        t0 = _time.perf_counter()
        try:
            out = self._execute_stmt(stmt)
            entry.state = "FINISHED"
            return out
        except Exception:
            entry.state = "FAILED"
            raise
        finally:
            entry.elapsed_ms = (_time.perf_counter() - t0) * 1e3
            if len(self.query_log) > 1000:
                del self.query_log[:-500]

    def plan(self, sql: str, optimized: bool = True) -> LogicalPlan:
        stmt = parse_statement(sql)
        if not isinstance(stmt, A.Query):
            raise ValueError("plan() takes a SELECT query")
        plan = plan_query(stmt, self.session)
        return optimize(plan, self.session) if optimized else plan

    # -- statement dispatch ---------------------------------------------------
    def _execute_stmt(self, stmt: A.Node) -> QueryResult:
        if isinstance(stmt, A.Query):
            plan = optimize(plan_query(stmt, self.session), self.session)
            return execute_plan(plan, self.session, self.rows_per_batch)
        if isinstance(stmt, A.Explain):
            if not isinstance(stmt.statement, A.Query):
                raise ValueError("EXPLAIN requires a query")
            import time as _time
            t0 = _time.perf_counter()
            plan = optimize(plan_query(stmt.statement, self.session),
                            self.session)
            stats = None
            if stmt.analyze:
                # EXPLAIN ANALYZE: run the query with per-operator stats,
                # draining batches without materializing client rows
                # (reference operator/ExplainAnalyzeOperator.java)
                from .stats import StatsCollector
                stats = StatsCollector(count_rows=True)
                stats.planning_s = _time.perf_counter() - t0
                t1 = _time.perf_counter()
                execute_plan(plan, self.session, self.rows_per_batch,
                             stats=stats, collect_rows=False)
                stats.total_wall_s = _time.perf_counter() - t1
            text = print_plan(plan, stats)
            return QueryResult(["Query Plan"], [T.VARCHAR],
                               [(line,) for line in text.split("\n")])
        if isinstance(stmt, A.ShowCatalogs):
            return QueryResult(["Catalog"], [T.VARCHAR],
                               [(c,) for c in self.session.catalogs.names()])
        if isinstance(stmt, A.ShowTables):
            conn = self.session.catalogs.get(self.session.catalog)
            return QueryResult(
                ["Table"], [T.VARCHAR],
                [(t,) for t in conn.metadata.list_tables()])
        if isinstance(stmt, A.ShowColumns):
            name = stmt.table
            catalog = self.session.catalog if len(name) < 3 else name[-3]
            schema = self.session.schema if len(name) < 2 else name[-2]
            conn = self.session.catalogs.get(catalog)
            ts = conn.metadata.table_schema(
                TableHandle(catalog, schema, name[-1]))
            return QueryResult(
                ["Column", "Type"], [T.VARCHAR, T.VARCHAR],
                [(f.name, f.type.display()) for f in ts.fields])
        if isinstance(stmt, A.ShowSession):
            return QueryResult(
                ["Name", "Value"], [T.VARCHAR, T.VARCHAR],
                [(k, str(v)) for k, v in
                 sorted(self.session.properties.items())])
        if isinstance(stmt, A.SetSession):
            value = _literal_value(stmt.value)
            self.session.properties[stmt.name] = value
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.ResetSession):
            self.session.properties.pop(stmt.name, None)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        if isinstance(stmt, A.CreateTableAsSelect):
            return self._ctas(stmt)
        if isinstance(stmt, A.InsertInto):
            return self._insert(stmt)
        if isinstance(stmt, A.DropTable):
            conn, table = self._writable(stmt.name)
            conn.drop_table(table, if_exists=stmt.if_exists)
            return QueryResult(["result"], [T.BOOLEAN], [(True,)])
        raise NotImplementedError(
            f"statement {type(stmt).__name__} is not supported yet")

    # -- write path (reference TableWriterOperator + finishInsert) ----------
    def _writable(self, name):
        catalog = self.session.catalog if len(name) < 3 else name[-3]
        conn = self.session.catalogs.get(catalog)
        if not hasattr(conn, "create_table"):
            raise ValueError(f"catalog {catalog!r} is not writable")
        return conn, name[-1]

    def _run_to_batches(self, query: A.Query):
        from ..batch import Schema
        from .local import _Executor, run_init_plans
        plan = optimize(plan_query(query, self.session), self.session)
        ex = _Executor(self.session, self.rows_per_batch)
        run_init_plans(ex, plan)
        root = plan.root
        schema = Schema([(f.name, f.type) for f in root.fields])
        return schema, ex.run(root.child)

    def _ctas(self, stmt: A.CreateTableAsSelect) -> QueryResult:
        conn, table = self._writable(stmt.name)
        schema, batches = self._run_to_batches(stmt.query)
        if table in conn.tables and stmt.if_not_exists:
            return QueryResult(["rows"], [T.BIGINT], [(0,)])
        conn.create_table(table, schema, if_not_exists=stmt.if_not_exists)
        n = 0
        for b in batches:
            n += conn.append(table, Batch(schema, b.columns, b.row_mask))
        return QueryResult(["rows"], [T.BIGINT], [(n,)])

    def _insert(self, stmt: A.InsertInto) -> QueryResult:
        conn, table = self._writable(stmt.name)
        schema, batches = self._run_to_batches(stmt.query)
        n = 0
        for b in batches:
            n += conn.append(table, Batch(schema, b.columns, b.row_mask))
        return QueryResult(["rows"], [T.BIGINT], [(n,)])


def _literal_value(e: A.Expression):
    if isinstance(e, A.StringLiteral):
        return e.value
    if isinstance(e, A.LongLiteral):
        return e.value
    if isinstance(e, A.DoubleLiteral):
        return e.value
    if isinstance(e, A.DecimalLiteral):
        return e.value
    if isinstance(e, A.BooleanLiteral):
        return e.value
    raise ValueError("session value must be a literal")

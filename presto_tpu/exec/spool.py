"""Page-addressed exchange spool: durable shuffle storage.

The role of the reference's spooling exchange manager (reference
presto-main fault-tolerant execution spools every exchange page to
external storage — a filesystem/object-store directory every node can
reach — so task retries replay pages instead of re-running producers,
and a drained worker's output outlives its process). PR 5's fault
tolerance used ``retain=True`` in-memory output buffers as an explicit
stand-in; this module is the real thing, grown out of
``exec/spill.py``'s :class:`~presto_tpu.exec.spill.SpillFile`:

- every output-buffer page is appended, **attempt-versioned** (the
  task id embeds the attempt suffix) and **token-addressed**, to a
  per-query directory of page logs, one
  ``<query>/<task_id>.b<buffer>.pages`` file per output buffer;
- each frame is **checksummed** (crc32) at write time and verified at
  read time — a corrupted page surfaces as
  :class:`SpoolCorruptionError`, which the exchange layer converts
  into an upstream-task failure so the retry layer re-runs the
  producer instead of serving garbage;
- a ``<task_id>.done`` marker (final token count per buffer) commits
  the attempt: readers treat a marker-less task as incomplete and fall
  back to normal retry semantics;
- disk usage is **accounted** against ``spool.max-bytes`` (writes past
  it raise :class:`SpoolFullError`) and **GC'd per query** on query
  end and abort (``release_query``), so the chaos suite can assert no
  orphaned per-query directories.

Frame layout (append-only, partial trailing frames are ignored by
readers — a writer killed mid-append never corrupts earlier pages)::

    [u32 token][u32 length][u32 crc32(payload)][payload bytes]

The store interface is pluggable (:class:`SpoolStore`); two backends
ship. :class:`LocalDiskSpoolStore` is append-only page logs on a local
filesystem, which doubles as "shared storage" whenever ``spool.dir``
points every node at one filesystem — exactly how the in-process test
clusters and single-host multi-worker deployments run.
:class:`ObjectSpoolStore` emulates a GCS/S3-style bucket:
whole-object, content-addressed (sha-256 digests; identical pages —
broadcast exchanges — are stored once and reference-counted),
manifest-committed, with a config-injected latency/bandwidth model on
every put/get so benchmarks and chaos runs pay realistic object-store
round trips. Because the bucket outlives every worker process, the
object backend is what lets the autoscaler scale the worker set to
ZERO mid-query and replay the shuffle from storage when capacity
returns. The process-wide instance is :data:`SPOOL` (a
:class:`SwitchableSpoolStore` facade over both backends), configured
via ``spool.dir`` / ``spool.max-bytes`` / ``spool.backend`` /
``spool.object.*`` in ``etc/config.properties``.

Failpoint sites (exec/failpoints.py): ``spool.write`` fails an append
(the producing task fails and retries), ``spool.read`` fails a page
read (the consumer treats the spool copy as lost), ``spool.corrupt`` —
armed with the ``error`` action — makes the write path deliberately
flip one payload byte while recording the ORIGINAL checksum, planting
a stored corruption for the read path to detect, and
``spool.object_put`` / ``spool.object_get`` fail one emulated
object-store upload/download (the object-backend analogues of
write/read, keyed the same way).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import struct
import tempfile
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import REGISTRY
from .failpoints import FAILPOINTS, FailpointError
from .spill import SpillFile

_WRITE_BYTES = REGISTRY.counter("spool_write_bytes_total")
_READ_BYTES = REGISTRY.counter("spool_read_bytes_total")
_CORRUPTIONS = REGISTRY.counter("spool_corruption_total")
_GC_BYTES = REGISTRY.counter("spool_gc_bytes_total")
_RESIDENT = REGISTRY.gauge("spool_resident_bytes")

_OBJ_PUTS = REGISTRY.counter("spool_object_put_total")
_OBJ_GETS = REGISTRY.counter("spool_object_get_total")
_OBJ_PUT_BYTES = REGISTRY.counter("spool_object_put_bytes_total")
_OBJ_GET_BYTES = REGISTRY.counter("spool_object_get_bytes_total")
_OBJ_DEDUP = REGISTRY.counter("spool_object_dedup_total")
_OBJ_RESIDENT = REGISTRY.gauge("spool_object_resident_bytes")
_OBJ_RTT = REGISTRY.histogram("spool_object_rtt_seconds")

_FRAME = struct.Struct("<III")          # token, length, crc32
DEFAULT_MAX_BYTES = 4 << 30


class SpoolCorruptionError(RuntimeError):
    """A spooled page failed its checksum (or went unreadable): the
    spool copy is unusable and the producer must be re-run."""


class SpoolFullError(RuntimeError):
    """The store is at ``spool.max-bytes``; the writing task fails
    (and retries once queries release their spool space)."""


class SpoolWriter:
    """One task attempt's write handle: page logs for each output
    buffer plus the completion marker. Single-threaded by construction
    (the task's producer thread is the only writer)."""

    def __init__(self, store: "LocalDiskSpoolStore", query_id: str,
                 task_id: str, n_buffers: int):
        self.store = store
        self.query_id = query_id
        self.task_id = task_id
        self.n_buffers = n_buffers
        self._files: Dict[int, SpillFile] = {}
        self._closed = False

    def _file(self, buffer_id: int) -> SpillFile:
        f = self._files.get(buffer_id)
        if f is None:
            path = self.store._page_path(self.query_id, self.task_id,
                                         buffer_id, create=True)
            f = self._files[buffer_id] = SpillFile(path=path,
                                                  delete=False)
        return f

    def append(self, buffer_id: int, token: int, page: bytes) -> None:
        key = f"{self.task_id}/{buffer_id}/{token}"
        FAILPOINTS.hit("spool.write", key=key, task_id=self.task_id)
        crc = zlib.crc32(page) & 0xFFFFFFFF
        try:
            # deliberate corruption injection: the frame keeps the
            # ORIGINAL checksum while one payload byte flips — the read
            # path must catch it (chaos scenario spool_corrupt)
            FAILPOINTS.hit("spool.corrupt", key=key,
                           task_id=self.task_id)
        except FailpointError:
            page = bytes([page[0] ^ 0xFF]) + page[1:] if page else page
        frame = _FRAME.pack(token, len(page), crc) + page
        self.store._reserve(self.query_id, len(frame))
        f = self._file(buffer_id)
        f.append(frame)
        f.flush()
        _WRITE_BYTES.inc(len(frame))

    def finish(self, next_tokens: List[int]) -> None:
        """Commit the attempt: every buffer's final token count becomes
        durable BEFORE the task announces FINISHED, so a consumer that
        sees the marker can trust the page logs are complete."""
        for f in self._files.values():
            f.flush()
        doc = json.dumps({"tokens": [int(t) for t in next_tokens]})
        path = self.store._done_path(self.query_id, self.task_id,
                                     create=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(doc)
        os.replace(tmp, path)
        self.store._reserve(self.query_id, len(doc))
        self.close()

    def abandon(self) -> None:
        """Drop a failed/aborted attempt's partial page logs now (the
        per-query GC at query end is the backstop)."""
        self.close()
        self.store._drop_task(self.query_id, self.task_id,
                              self.n_buffers)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files = {}


class SpoolStore:
    """Backend interface; implementations must be safe for concurrent
    writers (distinct task attempts) and readers."""

    def writer(self, query_id: str, task_id: str,
               n_buffers: int) -> SpoolWriter:
        raise NotImplementedError

    def finished_tokens(self, query_id: str,
                        task_id: str) -> Optional[List[int]]:
        raise NotImplementedError

    def read_pages(self, query_id: str, task_id: str, buffer_id: int,
                   token: int,
                   max_bytes: int = 8 << 20) -> Tuple[List[bytes], int]:
        raise NotImplementedError

    def release_query(self, query_id: str) -> int:
        raise NotImplementedError


class _FileIndex:
    """Incremental frame index over one append-only page log: repeated
    reads re-scan only bytes appended since the last scan. Owns its
    own lock so a cold scan of a large page log (disk I/O) never
    holds the store-wide lock that every producer's per-page
    ``_reserve`` takes."""

    __slots__ = ("scanned", "frames", "lock")

    def __init__(self):
        from .._devtools.lockcheck import checked_lock
        self.scanned = 0
        self.frames: Dict[int, Tuple[int, int, int]] = {}
        # token -> (payload offset, length, crc)
        self.lock = checked_lock("spool.file-index")


class LocalDiskSpoolStore(SpoolStore):
    """Local-filesystem backend: ``<dir>/<query_id>/`` per query.
    Point ``spool.dir`` at shared storage (NFS, a host-local dir for
    in-process clusters) and every node reads every node's pages —
    the property the drain fast-exit and worker-death replay paths
    rely on."""

    def __init__(self, directory: Optional[str] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        from .._devtools.lockcheck import checked_lock
        self._lock = checked_lock("spool.store")
        self._dir = directory
        self.max_bytes = int(max_bytes)
        self._query_bytes: Dict[str, int] = {}
        self._index: Dict[str, _FileIndex] = {}

    # -- configuration -------------------------------------------------------
    def configure(self, directory: Optional[str] = None,
                  max_bytes: Optional[int] = None) -> None:
        """Apply ``spool.dir`` / ``spool.max-bytes`` (config boot path;
        per-node, BEFORE any query runs)."""
        with self._lock:
            if directory:
                self._dir = directory
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)

    @property
    def directory(self) -> str:
        with self._lock:
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="presto-tpu-spool-")
            os.makedirs(self._dir, exist_ok=True)
            return self._dir

    # -- paths ---------------------------------------------------------------
    def _query_dir(self, query_id: str, create: bool = False) -> str:
        d = os.path.join(self.directory, query_id)
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    def _page_path(self, query_id: str, task_id: str, buffer_id: int,
                   create: bool = False) -> str:
        # readers never create: a late read after release_query must
        # not resurrect an empty per-query directory (the chaos suite
        # asserts zero orphans)
        return os.path.join(self._query_dir(query_id, create=create),
                            f"{task_id}.b{buffer_id}.pages")

    def _done_path(self, query_id: str, task_id: str,
                   create: bool = False) -> str:
        return os.path.join(self._query_dir(query_id, create=create),
                            f"{task_id}.done")

    # -- accounting ----------------------------------------------------------
    def _reserve(self, query_id: str, n: int) -> None:
        with self._lock:
            total = sum(self._query_bytes.values())
            if total + n > self.max_bytes:
                raise SpoolFullError(
                    f"spool at {total} of {self.max_bytes} bytes "
                    f"(spool.max-bytes); cannot append {n}")
            self._query_bytes[query_id] = \
                self._query_bytes.get(query_id, 0) + n
            _RESIDENT.set(total + n)

    def usage(self) -> Dict[str, int]:
        with self._lock:
            return {"bytes": sum(self._query_bytes.values()),
                    "queries": len(self._query_bytes),
                    "max_bytes": self.max_bytes}

    # -- write side ----------------------------------------------------------
    def writer(self, query_id: str, task_id: str,
               n_buffers: int) -> SpoolWriter:
        return SpoolWriter(self, query_id, task_id, n_buffers)

    # -- read side -----------------------------------------------------------
    def finished_tokens(self, query_id: str,
                        task_id: str) -> Optional[List[int]]:
        """The committed attempt's per-buffer token counts, or None
        while the attempt is incomplete/unknown (normal retry applies)."""
        path = self._done_path(query_id, task_id)
        try:
            with open(path, encoding="utf-8") as f:
                return [int(t) for t in json.load(f)["tokens"]]
        except (OSError, ValueError, KeyError):
            return None

    def _scan(self, idx: _FileIndex, path: str) -> None:
        """Extend the frame index over newly appended bytes (caller
        holds the INDEX lock, not the store lock). A partial trailing
        frame (writer mid-append) is left unindexed until it
        completes."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size <= idx.scanned:
            return
        with open(path, "rb") as f:
            f.seek(idx.scanned)
            off = idx.scanned
            while True:
                head = f.read(_FRAME.size)
                if len(head) < _FRAME.size:
                    break
                token, length, crc = _FRAME.unpack(head)
                if off + _FRAME.size + length > size:
                    break               # partial trailing frame
                idx.frames[token] = (off + _FRAME.size, length, crc)
                f.seek(length, os.SEEK_CUR)
                off += _FRAME.size + length
            idx.scanned = off

    def read_pages(self, query_id: str, task_id: str, buffer_id: int,
                   token: int,
                   max_bytes: int = 8 << 20) -> Tuple[List[bytes], int]:
        """Pages at/after ``token`` in token order (bounded by
        ``max_bytes``), with checksum verification. Returns
        ``(pages, next_token)``; an unreadable or checksum-failing page
        raises :class:`SpoolCorruptionError`."""
        path = self._page_path(query_id, task_id, buffer_id)
        with self._lock:
            idx = self._index.get(path)
            if idx is None:
                idx = self._index[path] = _FileIndex()
        with idx.lock:
            self._scan(idx, path)
            want: List[Tuple[int, Tuple[int, int, int]]] = []
            t = token
            while t in idx.frames:
                want.append((t, idx.frames[t]))
                t += 1
        out: List[bytes] = []
        nxt = token
        size = 0
        if not want:
            # nothing indexed at/after this token (unknown task,
            # abandoned attempt, or the writer hasn't got there yet):
            # an empty read, not an error — the caller's completion
            # marker decides whether more was promised
            return out, nxt
        try:
            with open(path, "rb") as f:
                for t, (off, length, crc) in want:
                    FAILPOINTS.hit(
                        "spool.read",
                        key=f"{task_id}/{buffer_id}/{t}",
                        task_id=task_id)
                    f.seek(off)
                    page = f.read(length)
                    if len(page) != length \
                            or (zlib.crc32(page) & 0xFFFFFFFF) != crc:
                        _CORRUPTIONS.inc()
                        raise SpoolCorruptionError(
                            f"spool page {task_id}/b{buffer_id}/t{t} "
                            f"failed checksum")
                    out.append(page)
                    _READ_BYTES.inc(len(page))
                    nxt = t + 1
                    size += length
                    if size >= max_bytes:
                        break
        except OSError as e:
            raise SpoolCorruptionError(
                f"spool page log {task_id}/b{buffer_id} unreadable: "
                f"{e}") from None
        return out, nxt

    # -- GC ------------------------------------------------------------------
    def _drop_task(self, query_id: str, task_id: str,
                   n_buffers: int) -> None:
        freed = 0
        paths = [self._done_path(query_id, task_id)] + [
            self._page_path(query_id, task_id, b)
            for b in range(n_buffers)]
        for p in paths:
            try:
                freed += os.path.getsize(p)
                os.unlink(p)
            except OSError:
                pass
            with self._lock:
                self._index.pop(p, None)
        # a straggler attempt appending AFTER its query's
        # release_query (abort sets the flag, the task thread may be
        # mid-append) briefly resurrects the per-query directory and
        # its accounting entry; its abandon() lands here — drop the
        # emptied directory and the zeroed entry so nothing orphans
        try:
            os.rmdir(os.path.join(self.directory, query_id))
        except OSError:
            pass                        # non-empty or already gone
        with self._lock:
            q = self._query_bytes.get(query_id, 0)
            if q - freed <= 0:
                self._query_bytes.pop(query_id, None)
            else:
                self._query_bytes[query_id] = q - freed
            _RESIDENT.set(sum(self._query_bytes.values()))
        if freed:
            _GC_BYTES.inc(freed)

    def release_query(self, query_id: str) -> int:
        """Remove the query's spool directory (query end / abort).
        Idempotent — coordinator and every worker may each release."""
        d = os.path.join(self.directory, query_id)
        with self._lock:
            freed = self._query_bytes.pop(query_id, 0)
            prefix = d + os.sep
            for p in [p for p in self._index if p.startswith(prefix)]:
                del self._index[p]
            _RESIDENT.set(sum(self._query_bytes.values()))
        shutil.rmtree(d, ignore_errors=True)
        if freed:
            _GC_BYTES.inc(freed)
        return freed

    def query_dirs(self) -> List[str]:
        """Per-query spool directories currently on disk (the chaos
        suite's no-orphans assertion)."""
        with self._lock:
            if self._dir is None or not os.path.isdir(self._dir):
                return []
            return sorted(
                e for e in os.listdir(self._dir)
                if os.path.isdir(os.path.join(self._dir, e)))


class ObjectSpoolWriter:
    """One task attempt's write handle against the object backend.
    Pages go up as content-addressed blobs immediately (durable before
    the buffer makes them visible); :meth:`finish` commits the attempt
    by uploading the manifest. Duck-types :class:`SpoolWriter`."""

    def __init__(self, store: "ObjectSpoolStore", query_id: str,
                 task_id: str, n_buffers: int):
        self.store = store
        self.query_id = query_id
        self.task_id = task_id
        self.n_buffers = n_buffers
        # buffer_id -> [(token, digest, length, crc), ...]
        self._entries: Dict[int, List[Tuple[int, str, int, int]]] = {}
        self._closed = False

    def append(self, buffer_id: int, token: int, page: bytes) -> None:
        key = f"{self.task_id}/{buffer_id}/{token}"
        FAILPOINTS.hit("spool.write", key=key, task_id=self.task_id)
        crc = zlib.crc32(page) & 0xFFFFFFFF
        digest = hashlib.sha256(page).hexdigest()[:32]
        try:
            # same deliberate-corruption contract as the disk backend:
            # digest and checksum are of the CLEAN page, the stored
            # blob carries one flipped byte for the read path to catch
            FAILPOINTS.hit("spool.corrupt", key=key,
                           task_id=self.task_id)
        except FailpointError:
            page = bytes([page[0] ^ 0xFF]) + page[1:] if page else page
        self.store._put_page(self.query_id, self.task_id, buffer_id,
                             token, digest, page, crc)
        self._entries.setdefault(buffer_id, []).append(
            (token, digest, len(page), crc))

    def finish(self, next_tokens: List[int]) -> None:
        """Commit the attempt: the manifest (per-buffer token counts +
        the full token -> blob map) uploads atomically BEFORE the task
        announces FINISHED — a reader that sees the manifest can trust
        every referenced blob is already durable."""
        self.store._put_manifest(
            self.query_id, self.task_id,
            {"tokens": [int(t) for t in next_tokens],
             "buffers": {str(b): [[t, d, ln, crc]
                                  for t, d, ln, crc in entries]
                         for b, entries in self._entries.items()}})
        self.close()

    def abandon(self) -> None:
        """Drop a failed/aborted attempt: decrement the blob refcounts
        this writer took and delete anything unreferenced now (the
        per-query GC at query end is the backstop)."""
        self.close()
        self.store._abandon_task(self.query_id, self.task_id,
                                 self._entries)
        self._entries = {}

    def close(self) -> None:
        self._closed = True


class ObjectSpoolStore(SpoolStore):
    """Emulated object-store backend: one "bucket" directory with
    whole-object puts/gets, per-query prefixes, and a config-injected
    latency/bandwidth model (``spool.object.put-latency-ms`` /
    ``get-latency-ms`` / ``bandwidth-mbps``) standing in for GCS/S3
    round trips.

    Layout under the bucket::

        <query_id>/blobs/<sha256-digest>       content-addressed pages
        <query_id>/manifests/<task_id>.json    the attempt commit marker

    Pages are content-addressed: identical payloads (broadcast
    exchange pages fan the same bytes to every consumer buffer) store
    ONE blob, reference-counted in process. A task attempt becomes
    visible to remote readers only when its manifest commits
    (atomic whole-object put), so a writer killed mid-upload leaves
    garbage blobs for query GC, never a torn attempt. Uncommitted
    pages remain readable to the OWNING process through a live
    in-memory index — the worker's own output buffer serves
    spool-evicted tokens from it before the attempt commits."""

    def __init__(self, directory: Optional[str] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 put_latency_s: float = 0.0,
                 get_latency_s: float = 0.0,
                 bandwidth_bytes_per_s: float = 0.0):
        from .._devtools.lockcheck import checked_lock
        self._lock = checked_lock("spool.object-store")
        self._dir = directory
        self.max_bytes = int(max_bytes)
        self.put_latency_s = float(put_latency_s)
        self.get_latency_s = float(get_latency_s)
        #: 0 = infinite (latency-only model)
        self.bandwidth_bytes_per_s = float(bandwidth_bytes_per_s)
        self._query_bytes: Dict[str, int] = {}
        #: query -> digest -> refcount (in-process; cross-process
        #: deployments fall back to query-end GC for shared blobs)
        self._refs: Dict[str, Dict[str, int]] = {}
        #: (query, task, buffer) -> {token: (digest, length, crc)} —
        #: the uncommitted-attempt index for the owning process
        self._live: Dict[Tuple[str, str, int],
                         Dict[int, Tuple[str, int, int]]] = {}
        #: committed manifests, cached (immutable once committed)
        self._manifests: Dict[Tuple[str, str], Dict] = {}

    # -- configuration -------------------------------------------------------
    def configure(self, directory: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  put_latency_s: Optional[float] = None,
                  get_latency_s: Optional[float] = None,
                  bandwidth_bytes_per_s: Optional[float] = None) -> None:
        with self._lock:
            if directory:
                self._dir = directory
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)
            if put_latency_s is not None:
                self.put_latency_s = float(put_latency_s)
            if get_latency_s is not None:
                self.get_latency_s = float(get_latency_s)
            if bandwidth_bytes_per_s is not None:
                self.bandwidth_bytes_per_s = float(bandwidth_bytes_per_s)

    @property
    def directory(self) -> str:
        with self._lock:
            if self._dir is None:
                self._dir = tempfile.mkdtemp(
                    prefix="presto-tpu-objspool-")
            os.makedirs(self._dir, exist_ok=True)
            return self._dir

    # -- the emulated wire ---------------------------------------------------
    def _transfer(self, n_bytes: int, latency_s: float) -> None:
        """Pay one modeled object-store round trip (outside any lock)."""
        delay = latency_s
        if self.bandwidth_bytes_per_s > 0:
            delay += n_bytes / self.bandwidth_bytes_per_s
        if delay > 0:
            time.sleep(delay)
        _OBJ_RTT.observe(delay)

    # -- paths ---------------------------------------------------------------
    def _blob_path(self, query_id: str, digest: str,
                   create: bool = False) -> str:
        d = os.path.join(self.directory, query_id, "blobs")
        if create:
            os.makedirs(d, exist_ok=True)
        return os.path.join(d, digest)

    def _manifest_path(self, query_id: str, task_id: str,
                       create: bool = False) -> str:
        d = os.path.join(self.directory, query_id, "manifests")
        if create:
            os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{task_id}.json")

    def _atomic_put(self, path: str, payload: bytes) -> None:
        tmp = f"{path}.up.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)

    # -- accounting ----------------------------------------------------------
    def _reserve_locked(self, query_id: str, n: int) -> None:
        total = sum(self._query_bytes.values())
        if total + n > self.max_bytes:
            raise SpoolFullError(
                f"object spool at {total} of {self.max_bytes} bytes "
                f"(spool.max-bytes); cannot put {n}")
        self._query_bytes[query_id] = \
            self._query_bytes.get(query_id, 0) + n
        _OBJ_RESIDENT.set(total + n)

    def usage(self) -> Dict[str, int]:
        with self._lock:
            return {"bytes": sum(self._query_bytes.values()),
                    "queries": len(self._query_bytes),
                    "max_bytes": self.max_bytes}

    # -- write side ----------------------------------------------------------
    def writer(self, query_id: str, task_id: str,
               n_buffers: int) -> ObjectSpoolWriter:
        return ObjectSpoolWriter(self, query_id, task_id, n_buffers)

    def _put_page(self, query_id: str, task_id: str, buffer_id: int,
                  token: int, digest: str, page: bytes,
                  crc: int) -> None:
        key = f"{task_id}/{buffer_id}/{token}"
        FAILPOINTS.hit("spool.object_put", key=key, task_id=task_id)
        with self._lock:
            refs = self._refs.setdefault(query_id, {})
            fresh = refs.get(digest, 0) == 0
            if fresh:
                self._reserve_locked(query_id, len(page))
            refs[digest] = refs.get(digest, 0) + 1
        if fresh:
            self._transfer(len(page), self.put_latency_s)
            self._atomic_put(
                self._blob_path(query_id, digest, create=True), page)
            _OBJ_PUTS.inc()
            _OBJ_PUT_BYTES.inc(len(page))
            _WRITE_BYTES.inc(len(page))
        else:
            # content-addressing pays off: the blob is already up —
            # one latency-only round trip confirms it
            self._transfer(0, self.put_latency_s)
            _OBJ_DEDUP.inc()
        with self._lock:
            self._live.setdefault((query_id, task_id, buffer_id), {})[
                token] = (digest, len(page), crc)

    def _put_manifest(self, query_id: str, task_id: str,
                      doc: Dict) -> None:
        FAILPOINTS.hit("spool.object_put", key=f"{task_id}/manifest",
                       task_id=task_id)
        payload = json.dumps(doc).encode()
        with self._lock:
            self._reserve_locked(query_id, len(payload))
        self._transfer(len(payload), self.put_latency_s)
        self._atomic_put(
            self._manifest_path(query_id, task_id, create=True), payload)
        _OBJ_PUTS.inc()
        _OBJ_PUT_BYTES.inc(len(payload))
        _WRITE_BYTES.inc(len(payload))
        with self._lock:
            self._manifests[(query_id, task_id)] = doc
            for k in [k for k in self._live
                      if k[0] == query_id and k[1] == task_id]:
                del self._live[k]

    def _abandon_task(self, query_id: str, task_id: str,
                      entries: Dict[int, List[Tuple[int, str, int, int]]]
                      ) -> None:
        doomed: List[Tuple[str, int]] = []
        with self._lock:
            refs = self._refs.get(query_id, {})
            for buf_entries in entries.values():
                for _t, digest, length, _crc in buf_entries:
                    n = refs.get(digest, 0) - 1
                    if n <= 0:
                        refs.pop(digest, None)
                        doomed.append((digest, length))
                    else:
                        refs[digest] = n
            for k in [k for k in self._live
                      if k[0] == query_id and k[1] == task_id]:
                del self._live[k]
            freed = sum(ln for _d, ln in doomed)
            q = self._query_bytes.get(query_id, 0)
            if q - freed <= 0:
                self._query_bytes.pop(query_id, None)
            else:
                self._query_bytes[query_id] = q - freed
            _OBJ_RESIDENT.set(sum(self._query_bytes.values()))
        for digest, _ln in doomed:
            try:
                os.unlink(self._blob_path(query_id, digest))
            except OSError:
                pass
        try:
            os.unlink(self._manifest_path(query_id, task_id))
        except OSError:
            pass
        if doomed:
            _GC_BYTES.inc(sum(ln for _d, ln in doomed))

    # -- read side -----------------------------------------------------------
    def _get_manifest(self, query_id: str, task_id: str
                      ) -> Optional[Dict]:
        with self._lock:
            doc = self._manifests.get((query_id, task_id))
        if doc is not None:
            return doc
        path = self._manifest_path(query_id, task_id)
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        self._transfer(size, self.get_latency_s)
        _OBJ_GETS.inc()
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode())
            doc["tokens"]
        except (OSError, ValueError, KeyError):
            # a torn/garbled manifest is an UNCOMMITTED attempt, not a
            # corruption: readers fall back to normal retry semantics
            return None
        _OBJ_GET_BYTES.inc(size)
        with self._lock:
            self._manifests[(query_id, task_id)] = doc
        return doc

    def finished_tokens(self, query_id: str,
                        task_id: str) -> Optional[List[int]]:
        doc = self._get_manifest(query_id, task_id)
        if doc is None:
            return None
        try:
            return [int(t) for t in doc["tokens"]]
        except (ValueError, TypeError, KeyError):
            return None

    def _frames_for(self, query_id: str, task_id: str, buffer_id: int
                    ) -> Dict[int, Tuple[str, int, int]]:
        """token -> (digest, length, crc), committed or live."""
        doc = self._get_manifest(query_id, task_id)
        if doc is not None:
            out: Dict[int, Tuple[str, int, int]] = {}
            for t, digest, length, crc in \
                    doc.get("buffers", {}).get(str(buffer_id), ()):
                out[int(t)] = (digest, int(length), int(crc))
            return out
        with self._lock:
            live = self._live.get((query_id, task_id, buffer_id))
            return dict(live) if live else {}

    def read_pages(self, query_id: str, task_id: str, buffer_id: int,
                   token: int,
                   max_bytes: int = 8 << 20) -> Tuple[List[bytes], int]:
        frames = self._frames_for(query_id, task_id, buffer_id)
        out: List[bytes] = []
        nxt = token
        size = 0
        t = token
        while t in frames:
            digest, length, crc = frames[t]
            key = f"{task_id}/{buffer_id}/{t}"
            FAILPOINTS.hit("spool.read", key=key, task_id=task_id)
            FAILPOINTS.hit("spool.object_get", key=key, task_id=task_id)
            self._transfer(length, self.get_latency_s)
            try:
                with open(self._blob_path(query_id, digest), "rb") as f:
                    page = f.read()
            except OSError as e:
                raise SpoolCorruptionError(
                    f"spool object {task_id}/b{buffer_id}/t{t} "
                    f"unreadable: {e}") from None
            _OBJ_GETS.inc()
            if len(page) != length \
                    or (zlib.crc32(page) & 0xFFFFFFFF) != crc:
                _CORRUPTIONS.inc()
                raise SpoolCorruptionError(
                    f"spool page {task_id}/b{buffer_id}/t{t} "
                    f"failed checksum")
            out.append(page)
            _READ_BYTES.inc(len(page))
            _OBJ_GET_BYTES.inc(len(page))
            nxt = t + 1
            size += length
            t += 1
            if size >= max_bytes:
                break
        return out, nxt

    # -- GC ------------------------------------------------------------------
    def release_query(self, query_id: str) -> int:
        """Delete the query's object prefix (query end / abort).
        Idempotent; zero orphaned objects is the chaos contract."""
        d = os.path.join(self.directory, query_id)
        with self._lock:
            freed = self._query_bytes.pop(query_id, 0)
            self._refs.pop(query_id, None)
            for k in [k for k in self._live if k[0] == query_id]:
                del self._live[k]
            for k in [k for k in self._manifests if k[0] == query_id]:
                del self._manifests[k]
            _OBJ_RESIDENT.set(sum(self._query_bytes.values()))
        shutil.rmtree(d, ignore_errors=True)
        if freed:
            _GC_BYTES.inc(freed)
        return freed

    def query_dirs(self) -> List[str]:
        with self._lock:
            if self._dir is None or not os.path.isdir(self._dir):
                return []
            return sorted(
                e for e in os.listdir(self._dir)
                if os.path.isdir(os.path.join(self._dir, e)))


class SwitchableSpoolStore(SpoolStore):
    """The process-wide facade over both backends. Call sites
    (``SPOOL.writer/finished_tokens/read_pages/release_query``)
    delegate to whichever backend ``spool.backend`` selected; switching
    applies to queries that START after the switch — an in-flight
    query must finish on the backend it began on (the config boot path
    switches before any query runs; chaos switches between queries)."""

    def __init__(self):
        self._local = LocalDiskSpoolStore()
        self._object = ObjectSpoolStore()
        self._impl: SpoolStore = self._local

    @property
    def backend(self) -> str:
        return "object" if self._impl is self._object else "local"

    @property
    def object_store(self) -> ObjectSpoolStore:
        return self._object

    @property
    def local_store(self) -> LocalDiskSpoolStore:
        return self._local

    def configure(self, directory: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  backend: Optional[str] = None,
                  object_dir: Optional[str] = None,
                  object_put_latency_s: Optional[float] = None,
                  object_get_latency_s: Optional[float] = None,
                  object_bandwidth_mbps: Optional[float] = None) -> None:
        """Apply ``spool.*`` config (boot path / chaos harness)."""
        self._local.configure(directory=directory, max_bytes=max_bytes)
        bw = None if object_bandwidth_mbps is None \
            else float(object_bandwidth_mbps) * 1e6 / 8.0
        self._object.configure(
            directory=object_dir, max_bytes=max_bytes,
            put_latency_s=object_put_latency_s,
            get_latency_s=object_get_latency_s,
            bandwidth_bytes_per_s=bw)
        if backend is not None:
            if backend not in ("local", "object"):
                raise ValueError(
                    f"spool.backend must be local or object, "
                    f"got {backend!r}")
            self._impl = self._object if backend == "object" \
                else self._local

    def writer(self, query_id: str, task_id: str, n_buffers: int):
        return self._impl.writer(query_id, task_id, n_buffers)

    def finished_tokens(self, query_id: str,
                        task_id: str) -> Optional[List[int]]:
        return self._impl.finished_tokens(query_id, task_id)

    def read_pages(self, query_id: str, task_id: str, buffer_id: int,
                   token: int,
                   max_bytes: int = 8 << 20) -> Tuple[List[bytes], int]:
        return self._impl.read_pages(query_id, task_id, buffer_id,
                                     token, max_bytes)

    def release_query(self, query_id: str) -> int:
        freed = 0
        # never-touched backends (no directory yet) have nothing to
        # free — skip them so release doesn't materialize temp dirs
        if self._local._dir is not None:
            freed += self._local.release_query(query_id)
        if self._object._dir is not None:
            freed += self._object.release_query(query_id)
        return freed

    def usage(self) -> Dict[str, int]:
        return self._impl.usage()

    def query_dirs(self) -> List[str]:
        """Union across backends (the chaos no-orphans sweep must see
        leftovers no matter which backend a query ran on)."""
        return sorted(set(self._local.query_dirs())
                      | set(self._object.query_dirs()))


#: the process-wide store (every worker/coordinator in this process
#: shares it; separate processes share through ``spool.dir`` /
#: ``spool.object.dir`` pointing at common storage)
SPOOL = SwitchableSpoolStore()

"""Distributed plan executor: SPMD stages over a device mesh.

The TPU-native form of the reference's distributed execution stack
(reference presto-main/.../sql/planner/PlanFragmenter.java:106 splits the
plan at exchanges; execution/scheduler/SqlQueryScheduler.java:533 runs the
stage DAG; operator/PartitionedOutputOperator.java:48 +
operator/ExchangeClient.java implement the shuffle). Here:

- a worker's share of a stage is a SHARD of one SPMD program over the mesh
  axis, not a process: batches live as globally-sharded arrays
  (NamedSharding over "dp"), so elementwise stages (scan-filter-project)
  parallelize via GSPMD with zero collectives;
- exchanges are collectives inside shard_map: FIXED_HASH distribution is
  the quota-compacted all_to_all over ICI (repartition_by_hash_compact),
  FIXED_BROADCAST is a device-to-device all-gather of the build side,
  GATHER (final output / merge) is an all_gather; no operator stages
  batches through the host — sort/top-n/window/unnest run shard-local
  with one collective merge;
- aggregation splits into partial (shard-local) -> hash exchange -> final,
  exactly Presto's PARTIAL/FINAL AggregationNode split, but fused into one
  jitted program per stage instead of two tasks and a wire format.

Scan splits are assigned round-robin to shards (reference
execution/scheduler/UniformNodeSelector.java role); each chunk becomes one
globally-sharded batch with equal per-shard capacity.
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:                                    # jax >= 0.6: top-level export,
    from jax import shard_map           # replication check is check_vma
    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:                     # jax 0.4.x: experimental module,
    from jax.experimental.shard_map import shard_map  # kwarg check_rep
    _SHARD_MAP_CHECK_KW = "check_rep"
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity, concat_batches
from ..expr import ir
from ..expr.compiler import compile_filter, compile_projection
from ..obs import flight as _flight
from ..obs.metrics import REGISTRY
from ..obs.trace import TRACER
from ..ops.aggregation import AggSpec, global_aggregate, grouped_aggregate
from ..ops.join import (
    build_match_mask, expand_join, lookup_join, match_count_max,
    semi_join_mask,
)
from ..ops.sort import SortKey, limit as limit_kernel, sort_batch, top_n
from ..parallel.exchange import partition_counts
from ..parallel.mesh import make_mesh
from ..planner.plan import (
    AggregationNode, DistinctNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, SemiJoinNode, SortNode,
    TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from ..planner.planner import LogicalPlan, Session, bool_property
from .local import QueryResult, _Executor, _plan_schema

#: mesh-path auto-selection observable: one count per query the router
#: placed on the SPMD substrate (the signal the default-on tests and
#: the MULTICHIP bench assert on)
_MESH_SELECTED = REGISTRY.counter("mesh_path_selected_total")
#: adaptive re-splits: one count per hot-bucket re-assignment a
#: _PartitionMap performed mid-query (StageMonitor's skew verdict
#: turned into action)
_MESH_RESPLITS = REGISTRY.counter("mesh_repartition_resplit_total")
#: host dispatches onto the mesh: one count per ``_smap`` program
#: invocation (the dotted tail labels the issuing stage kind). The
#: fused-exchange win is this counter's per-query delta shrinking ~3x+,
#: not just wall attribution — the MULTICHIP bench records the ratio
_MESH_DISPATCHES = REGISTRY.counter("mesh_dispatches_total")

#: cached 1-D meshes per device count (Mesh construction is cheap, but
#: a stable object keeps sharding identity stable across queries)
_MESH_CACHE: Dict[int, jax.sharding.Mesh] = {}

#: cross-query shard_map program cache: (call site, closure value
#: signature, specs, donate, mesh) -> _TimedEntry. A fresh executor per
#: query used to rebuild every jax.jit(shard_map(...)) object, so even
#: a WARM query paid a full re-trace per program — the last head of the
#: dispatch tax after the fused exchange removed the per-round one.
#: ops/jitcache.program_signature proves a closure only captures
#: value-stable state (plan nodes, schemas, key tuples, quotas); any
#: program it cannot prove keeps compile-per-query behavior. Bounded
#: LRU: assignment tuples from adaptive re-splits would otherwise grow
#: the cache without limit on a long-lived server.
_PROGRAM_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_PROGRAM_CACHE_CAP = 512
_PROGRAM_CACHE_LOCK = threading.Lock()
_PROGRAM_HITS = REGISTRY.counter("mesh_program_cache_hit_total")
_PROGRAM_MISSES = REGISTRY.counter("mesh_program_cache_miss_total")


class _FlightDispatch:
    """Wraps an ``_smap`` executable so every host-side dispatch counts
    in ``mesh_dispatches_total`` (dotted tail = issuing stage kind) and
    — when a flight recorder is active and ``kind`` is not None — lands
    as one flight round (obs/flight.py). Call semantics are
    untouched."""

    __slots__ = ("entry", "kind", "rounds", "_stage_counter")

    def __init__(self, entry, kind: Optional[str], stage: str = "misc",
                 rounds: int = 1):
        self.entry = entry
        self.kind = kind
        #: device exchange rounds one dispatch covers: a fused
        #: ``lax.fori_loop`` program amortizes R rounds behind a single
        #: host touch, and the flight record says so instead of
        #: undercounting the loop
        self.rounds = max(int(rounds), 1)
        self._stage_counter = REGISTRY.counter(
            f"mesh_dispatches_total.{stage}")

    def __call__(self, *args):
        _MESH_DISPATCHES.inc()
        self._stage_counter.inc()
        fl = _flight.current_flight()
        if fl is None or self.kind is None:
            return self.entry(*args)
        t0 = time.perf_counter()
        out = self.entry(*args)
        fl.record(self.kind, wall=time.perf_counter() - t0,
                  rounds=self.rounds)
        return out


def _batch_row_bytes(batch: Batch) -> int:
    """Rough per-row wire width (column storage + validity + mask) —
    sizes the flight recorder's bytes-moved estimate for an exchange
    round without touching device data."""
    return sum(c.data.dtype.itemsize + 1 for c in batch.columns) + 1


@contextlib.contextmanager
def _sync_record(what: str, kind: str = "sync"):
    """A ``device-sync`` trace span that ALSO records the host-blocking
    interval as a flight round (the control_sync/staging/drain buckets
    of the mesh attribution). The executable dispatched inside one of
    these intervals must be built with ``flight_kind=None`` so its wall
    isn't counted twice."""
    fl = _flight.current_flight()
    t0 = time.perf_counter() if fl is not None else 0.0
    try:
        with TRACER.span("device-sync", what=what):
            yield
    finally:
        if fl is not None:
            fl.record(kind, wall=time.perf_counter() - t0)


def _drain_inputs(*values) -> None:
    """Wait out the device arrays feeding a control-scalar fetch,
    recorded as a ``drain`` flight round (device_compute bucket). On an
    async backend the blocking wall at a ``_sync_record`` site is
    dominated by upstream compute still in flight — without this
    bracket that compute smears into ``control_sync`` exactly when the
    fused exchange shrinks the real control plane, and the bucket
    budgets gate on a lie. After the drain, the sync bracket times only
    the control round trip itself."""
    fl = _flight.current_flight()
    t0 = time.perf_counter() if fl is not None else 0.0
    try:
        with TRACER.span("device-sync", what="input-drain"):
            jax.block_until_ready([v for v in values if v is not None])
    finally:
        if fl is not None:
            fl.record("drain", wall=time.perf_counter() - t0)


def mesh_mode(session) -> str:
    """Resolved ``mesh_execution`` mode: the session property when set,
    else the ``PRESTO_TPU_MESH_EXECUTION`` environment default, else
    ``auto`` (mesh whenever >1 device is visible and the plan cuts into
    mesh stages)."""
    v = session.properties.get("mesh_execution")
    if v is None:
        v = os.environ.get("PRESTO_TPU_MESH_EXECUTION", "auto")
    return str(v).lower()


def mesh_flight_on(session) -> bool:
    """Resolved ``mesh_flight`` switch: the session property when set,
    else the ``PRESTO_TPU_MESH_FLIGHT`` environment default, else on —
    the recorder is cheap enough (asserted <1% in tests) to fly every
    mesh query."""
    v = session.properties.get("mesh_flight")
    if v is None:
        return os.environ.get(
            "PRESTO_TPU_MESH_FLIGHT", "on").lower() \
            not in ("off", "0", "false")
    return bool(v)


def mesh_device_count(session) -> int:
    """Effective mesh width: every visible device, clamped by the
    ``mesh_devices`` session property when positive."""
    have = len(jax.devices())
    want = int(session.properties.get("mesh_devices", 0) or 0)
    return min(want, have) if want > 0 else have


def _walk_scans(node) -> Iterator[TableScanNode]:
    if isinstance(node, TableScanNode):
        yield node
    for c in node.children:
        yield from _walk_scans(c)


#: memoized router verdicts per LogicalPlan identity: the serving hot
#: path re-executes one cached plan thousands of times, and the
#: O(plan-size) fragmenter walk must run once per plan, not once per
#: query. Entries carry a weakref to the plan and only serve while it
#: still points at the same live object — id reuse after GC can never
#: resurrect a dead plan's verdict. Lock-guarded: concurrent serving
#: queries route through here on many threads (lockcheck: leaf lock,
#: never held across a dispatch).
_PLAN_VERDICTS: Dict[int, Tuple[object, Tuple[bool, bool, str]]] = {}
from .._devtools.lockcheck import checked_lock
_PLAN_VERDICTS_LOCK = checked_lock("distributed.plan_verdicts")


def _plan_mesh_verdict(plan: LogicalPlan) -> Tuple[bool, bool, str]:
    """(fragments-into-mesh-stages, reads-real-data, reason)."""
    import weakref
    key = id(plan)
    with _PLAN_VERDICTS_LOCK:
        hit = _PLAN_VERDICTS.get(key)
        if hit is not None and hit[0]() is plan:
            return hit[1]
    from ..planner.fragmenter import plan_mesh_stages
    roots = [plan.root] + list(plan.init_plans)
    supported, reason = True, ""
    for r in roots:
        mp = plan_mesh_stages(r)
        if not mp.supported:
            supported, reason = False, mp.reason
            break
    scans = [s for r in roots for s in _walk_scans(r)]
    scannable = bool(scans) and all(s.catalog != "system"
                                    for s in scans)
    verdict = (supported, scannable, reason)
    with _PLAN_VERDICTS_LOCK:
        if len(_PLAN_VERDICTS) > 512:
            # evict dead plans first, then oldest-inserted live ones —
            # never clear(): wiping live cached plans' verdicts would
            # re-run the fragmenter walk on exactly the hot path this
            # memo exists for
            for k in [k for k, (ref, _) in _PLAN_VERDICTS.items()
                      if ref() is None]:
                _PLAN_VERDICTS.pop(k, None)
            while len(_PLAN_VERDICTS) > 512:
                _PLAN_VERDICTS.pop(next(iter(_PLAN_VERDICTS)), None)
        _PLAN_VERDICTS[key] = (weakref.ref(plan), verdict)
    return verdict


def select_mesh(session: Session,
                plan: LogicalPlan) -> Optional[jax.sharding.Mesh]:
    """The mesh auto-router: the Mesh this query should execute on, or
    None for the single-device path. ``auto`` (the default) selects the
    mesh when more than one device is effective, the plan (init plans
    included) cuts into mesh stages (planner/fragmenter.plan_mesh_stages)
    and the query reads real data (system-catalog metadata queries gain
    nothing from SPMD); ``on`` forces the mesh — an unfragmentable plan
    then raises instead of silently degrading; ``off`` never meshes."""
    mode = mesh_mode(session)
    if mode == "off":
        return None
    n = mesh_device_count(session)
    if n < 2 and mode != "on":
        return None
    supported, scannable, reason = _plan_mesh_verdict(plan)
    if not supported:
        if mode == "on":
            raise NotImplementedError(
                f"mesh_execution=on: plan has no mesh form ({reason})")
        return None
    if mode != "on" and not scannable:
        return None
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        mesh = _MESH_CACHE[n] = make_mesh(max(n, 1))
    _MESH_SELECTED.inc()
    return mesh


#: bucket subdivisions per shard in the adaptive exchange: B = n*4
#: buckets give the greedy re-balancer ~25%-of-a-shard granularity
#: without growing the quota readback beyond a few hundred scalars
_RESPLIT_FACTOR = 4


def _skew_ratio() -> float:
    """One engine-wide definition of "skewed": the coordinator
    StageMonitor's verdict ratio (exec/cluster.py, PR 3) also decides
    when the mesh exchange re-splits hot buckets."""
    from .cluster import StageMonitor
    return float(StageMonitor.skew_ratio)


def _per_dest_quota(counts: np.ndarray, assign: Sequence[int],
                    n: int) -> int:
    """Max live rows any (src shard, dst shard) pair ships under
    ``assign``: the static quota the compacted exchange needs."""
    a = np.asarray(assign)
    worst = 1
    for d in range(n):
        sel = counts[:, a == d]
        if sel.size:
            worst = max(worst, int(sel.sum(axis=1).max()))
    return worst


class _PartitionMap:
    """Bucket -> shard assignment shared by every exchange of one
    operator. Both sides of a partitioned join ship through ONE map, so
    equal keys colocate under ANY assignment (keys hash to buckets,
    buckets move atomically). The map observes per-bucket live counts
    as batches flow and re-splits hot buckets between batches: when one
    shard's load crosses the StageMonitor skew ratio over the median
    shard and a greedy LPT re-balance of bucket totals actually lowers
    the max, the assignment flips, ``epoch`` bumps, and the owning
    operator re-ships its prepared side under the new map."""

    #: re-balancing converges or it stops — never thrash the build side
    MAX_CHANGES = 2

    def __init__(self, n: int, adaptive: bool = True,
                 ratio: Optional[float] = None):
        self.n = n
        self.buckets = n * _RESPLIT_FACTOR
        self.assign: Tuple[int, ...] = tuple(
            b % n for b in range(self.buckets))
        self.epoch = 0
        self.adaptive = bool(adaptive) and n > 1
        self.ratio = float(ratio) if ratio is not None else _skew_ratio()
        self.changes = 0
        self._totals = np.zeros(self.buckets, dtype=np.int64)

    def observe(self, counts: np.ndarray) -> None:
        """Fold one batch's [n_src, buckets] live counts in; maybe
        re-assign."""
        if not self.adaptive:
            return
        t0 = time.perf_counter()
        self._totals += counts.sum(axis=0, dtype=np.int64)
        if self.changes >= self.MAX_CHANGES:
            return
        loads = np.zeros(self.n, dtype=np.int64)
        np.add.at(loads, np.asarray(self.assign), self._totals)
        # skew verdict against the BALANCED load (total/n), not the
        # median: with most shards idle the median collapses to zero
        # and a median test would never fire exactly when it matters
        fair = float(self._totals.sum()) / self.n
        if fair < 1.0 or float(loads.max()) <= self.ratio * fair:
            return
        new = self._greedy()
        new_loads = np.zeros(self.n, dtype=np.int64)
        np.add.at(new_loads, np.asarray(new), self._totals)
        if new == self.assign or new_loads.max() >= loads.max():
            return            # a single hot KEY cannot be split further
        self.assign = new
        self.epoch += 1
        self.changes += 1
        _MESH_RESPLITS.inc()
        fl = _flight.current_flight()
        if fl is not None:
            fl.record("resplit", wall=time.perf_counter() - t0,
                      rows=int(self._totals.sum()),
                      loads=[int(x) for x in new_loads])

    def _greedy(self) -> Tuple[int, ...]:
        """LPT: heaviest bucket first onto the least-loaded shard."""
        order = np.argsort(-self._totals, kind="stable")
        loads = [0] * self.n
        out = [0] * self.buckets
        for b in order:
            d = min(range(self.n), key=lambda i: (loads[i], i))
            out[int(b)] = d
            loads[d] += int(self._totals[int(b)])
        return tuple(out)


#: deferred skew checks in the fused exchange: device-side bucket
#: counts are fetched and folded into the _PartitionMap once per this
#: many rounds (minus the in-flight newest — see observe_pending), so
#: the host control plane touches the device once per stage-ish instead
#: of once per round and re-splits become a rarer loop-exit path
_FUSED_OBSERVE_EVERY = 4

#: per-shard slot ceiling for the fused aggregation carry — a grouping
#: only rides the multi-round fori_loop when its dense key domain proves
#: the state fits this many slots on every round (the PR 2/PR 10
#: stats-bounded-capacity contract applied to loop-invariant shapes)
_FUSED_STATE_SLOTS = 1 << 15
#: gathered-state ceiling (global rows) under which the fused finisher
#: replaces the hash-exchange + final pair with ONE all-gather + final
#: dispatch, masking all but shard 0 (the _global_agg pattern)
_FUSED_GATHER_SLOTS = 1 << 17


class _Repartitioner:
    """Quota-compacted bucket-hash exchange driver, two control planes:

    - **fused** (default, ``mesh_fused_exchange``): bucket-count + ship
      run as ONE collective program per round (exchange.
      repartition_fused) under a capacity-safe static quota, so a round
      is a single dispatch with no quota readback. Per-bucket counts
      ride along as a device-resident second output; the host folds
      them into the shared _PartitionMap only at deferred observe
      points (builds force one; probe loops check every
      _FUSED_OBSERVE_EVERY rounds, lagging one round so the fetch never
      blocks on an in-flight dispatch) — control scalars once per
      stage, re-splits preserved as a rarer loop-exit-and-rebuild path.
    - **classic** (escape hatch / tight-wire callers): one cheap
      collective reads per-(src, bucket) live counts, the host sizes
      the static quota and may re-balance hot buckets, and the exchange
      ships exactly quota slots per peer (wire cost ~C instead of the
      masked all_to_all's n*C; reference operator/
      PartitionedOutputOperator.java PagePartitioner).

    Jitted exchanges are cached per (assignment, quota bucket)."""

    def __init__(self, ex: "DistributedExecutor",
                 key_cols: Sequence[int], pmap: _PartitionMap,
                 fused: Optional[bool] = None):
        self.ex = ex
        self.keys = tuple(key_cols)
        self.map = pmap
        self.fused = (ex.fused_exchange if fused is None else bool(fused))
        self._counts_fn = None
        self._fns: Dict[Tuple, object] = {}
        self._fused_fns: Dict[Tuple, object] = {}
        self._last_counts: Optional[np.ndarray] = None
        #: device-resident [n*buckets] count vectors awaiting observe
        self._pending: List[object] = []
        self._rounds_since_observe = 0

    @property
    def epoch(self) -> int:
        return self.map.epoch

    def _counts(self, batch: Batch) -> np.ndarray:
        if self._counts_fn is None:
            self._counts_fn = self.ex._smap(
                lambda b, _k=self.keys, _bk=self.map.buckets:
                partition_counts(b, _k, _bk), 1,
                flight_kind=None, stage="exchange")
        _drain_inputs(batch)
        with _sync_record("exchange-quota"):
            raw = np.asarray(jax.device_get(self._counts_fn(batch)))
        return raw.reshape(self.ex.n, self.map.buckets)

    # -- fused control plane --------------------------------------------------
    def fused_quota(self, batch: Batch) -> int:
        """Capacity-safe static quota: any per-(src, dst) live count is
        bounded by the source shard's lane count, so this quota can
        never drop a row and needs no counts readback."""
        return bucket_capacity(max(batch.capacity // self.ex.n, 1))

    def note_counts(self, counts, rows_hint: int = 0) -> None:
        """Queue one fused round's device-side bucket counts for a
        deferred skew check (and keep the exchange-round metrics
        continuous with the classic plane)."""
        REGISTRY.counter("exchange_repartitions_total").inc()
        if not self.map.adaptive:
            return
        self._pending.append(counts)
        self._rounds_since_observe += 1
        if self._rounds_since_observe >= _FUSED_OBSERVE_EVERY:
            # pipelined check: leave the newest round's counts pending
            # so the device_get only touches rounds that already
            # retired — the fetch never stalls on in-flight compute
            self.observe_pending(keep_newest=len(self._pending) > 1)

    def observe_pending(self, keep_newest: bool = False) -> None:
        """Fetch queued device counts ONCE and fold them into the
        shared _PartitionMap — the per-stage control-scalar sync of the
        fused plane (builds call this; probe loops hit it every
        _FUSED_OBSERVE_EVERY rounds)."""
        take = self._pending[:-1] if keep_newest else self._pending
        if not take:
            return
        self._pending = self._pending[-1:] if keep_newest else []
        self._rounds_since_observe = len(self._pending)
        total = np.zeros((self.ex.n, self.map.buckets), dtype=np.int64)
        _drain_inputs(*take)
        with _sync_record("exchange-skew-check"):
            for c in take:
                total += np.asarray(jax.device_get(c)).reshape(
                    self.ex.n, self.map.buckets)
        self._last_counts = total
        self.map.observe(total)

    def _fused_ship(self, batch: Batch,
                    record_counts: bool = True) -> Batch:
        from .failpoints import FAILPOINTS
        fl = _flight.current_flight()
        t0 = time.perf_counter()
        FAILPOINTS.hit("mesh.repartition")
        assign = self.map.assign
        quota = self.fused_quota(batch)
        key = (assign, quota)
        fn = self._fused_fns.get(key)
        if fn is None:
            from ..parallel.exchange import repartition_fused
            fn = self._fused_fns[key] = self.ex._smap(
                lambda b, _k=self.keys, _ax=self.ex.axis,
                _n=self.ex.n, _a=assign, _q=quota: repartition_fused(
                    b, _k, _ax, _n, _a, _q), 1,
                n_out=2, flight_kind=None, stage="exchange")
        out, counts = fn(batch)
        if record_counts:
            self.note_counts(counts)
        else:
            # replay rounds still SHIP (the exchange-round ledger stays
            # whole) — they just don't fold counts in twice
            REGISTRY.counter("exchange_repartitions_total").inc()
        if fl is not None:
            # one record per fused exchange round; the failpoint rides
            # inside the timed span exactly like the classic _ship (row
            # loads stay device-resident — that's the point)
            fl.record("repartition", wall=time.perf_counter() - t0)
        return out

    def _ship(self, batch: Batch, counts: np.ndarray) -> Batch:
        from .failpoints import FAILPOINTS
        fl = _flight.current_flight()
        t0 = time.perf_counter()
        FAILPOINTS.hit("mesh.repartition")
        assign = self.map.assign
        quota = bucket_capacity(
            _per_dest_quota(counts, assign, self.ex.n))
        key = (assign, quota)
        fn = self._fns.get(key)
        if fn is None:
            from ..parallel.exchange import repartition_by_buckets_compact
            fn = self._fns[key] = self.ex._smap(
                lambda b, _k=self.keys, _ax=self.ex.axis,
                _n=self.ex.n, _a=assign, _q=quota:
                repartition_by_buckets_compact(
                    b, _k, _ax, _n, _a, _q), 1,
                flight_kind=None, stage="exchange")
        REGISTRY.counter("exchange_repartitions_total").inc()
        out = fn(batch)
        if fl is not None:
            # per-dest row loads under the CURRENT assignment: the
            # round's straggler signal for the critical path
            loads = np.zeros(self.ex.n, dtype=np.int64)
            np.add.at(loads, np.asarray(assign),
                      counts.sum(axis=0, dtype=np.int64))
            rows = int(loads.sum())
            fl.record("repartition", wall=time.perf_counter() - t0,
                      rows=rows, nbytes=rows * _batch_row_bytes(batch),
                      loads=[int(x) for x in loads])
        return out

    def __call__(self, batch: Batch) -> Batch:
        if self.fused:
            return self._fused_ship(batch)
        counts = self._counts(batch)
        self._last_counts = counts
        self.map.observe(counts)
        return self._ship(batch, counts)

    def replay(self, batch: Batch) -> Batch:
        """Re-ship a batch this exchange already observed (the join's
        build side after a probe-driven re-split) under the CURRENT
        assignment, without folding its counts in twice."""
        if self.fused:
            return self._fused_ship(batch, record_counts=False)
        counts = (self._last_counts if self._last_counts is not None
                  else self._counts(batch))
        return self._ship(batch, counts)


class DistributedExecutor(_Executor):
    """Executes a logical plan with data sharded over a mesh axis.

    Inherits the streaming structure of the local executor; overrides the
    exchange-bearing nodes (scan placement, aggregation, join, semi join,
    sort/top-n/distinct finalization) with SPMD implementations.
    """

    compact_streams = False   # compact() on a mesh-sharded batch would
    #                            gather it across devices; shard-local
    #                            compaction happens in the exchange path

    def __init__(self, session: Session, rows_per_batch: int,
                 mesh: jax.sharding.Mesh, stats=None):
        super().__init__(session, rows_per_batch, stats=stats)
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n = mesh.shape[self.axis]
        self._row_sharding = NamedSharding(mesh, P(self.axis))
        self._replicated = NamedSharding(mesh, P())
        #: memoized all-gather identity (see _replicate_device): one
        #: trace per executor, not one per broadcast build side
        self._replicate_jit = None
        #: fused SPMD exchange (default on): counts + ship collapse
        #: into one collective program per round, stats-bounded stages
        #: loop multiple rounds inside one dispatch, and control
        #: scalars are fetched once per stage. mesh_fused_exchange=off
        #: is the escape hatch back to the per-round host control plane
        self.fused_exchange = bool_property(session, "mesh_fused_exchange",
                                            True)
        #: cap on chunks one fused lax.fori_loop dispatch may stack
        #: (bounds resident memory: the stacked wave holds every chunk)
        try:
            self.fused_loop_rounds = max(int(
                session.properties.get("mesh_fused_loop_rounds", 32)), 1)
        except (TypeError, ValueError):
            self.fused_loop_rounds = 32

    # -- sharding helpers ----------------------------------------------------
    def _shard_rows(self, batch: Batch) -> Batch:
        """Place a host-built batch row-sharded across the mesh."""
        put = lambda x: jax.device_put(x, self._row_sharding)
        cols = [Column(c.type, put(c.data), put(c.validity), c.dictionary)
                for c in batch.columns]
        return Batch(batch.schema, cols, put(batch.row_mask))

    def _smap(self, fn, n_in: int, replicated_in: Sequence[int] = (),
              n_out: int = 1, replicated_out=False,
              flight_kind: Optional[str] = "dispatch",
              stage: str = "misc", donate: Sequence[int] = (),
              rounds: int = 1):
        in_specs = tuple(
            P() if i in replicated_in else P(self.axis)
            for i in range(n_in))
        # replicated_out: every shard computes the identical value (e.g.
        # preparing a replicated build side), so the output stays P() —
        # specs are PREFIX pytrees, so one spec covers a whole prepared
        # tuple of arrays. True replicates every output; a sequence
        # names the replicated output POSITIONS (a fused program can
        # ship a sharded batch plus a replicated control scalar)
        if isinstance(replicated_out, bool):
            rep_out = (set(range(n_out)) if replicated_out else set())
        else:
            rep_out = set(replicated_out)
        out_specs = ((P() if 0 in rep_out else P(self.axis))
                     if n_out == 1
                     else tuple(P() if i in rep_out else P(self.axis)
                                for i in range(n_out)))
        # registered entry, not a raw jax.jit: every shard_map program
        # is an executable like any jitcache kernel — compiles and
        # (profiled) device time land in obs.profiler.EXECUTABLES
        # instead of being invisible to the PR 6 cost plane. The static
        # key is the defining CALL SITE (code object) + specs:
        # anonymous lambdas from different sites must not collapse into
        # one 'smap:<lambda>' record (that would sum unrelated
        # operators' compiles/FLOPs into one executables row), while
        # re-builds of the same program share one record instead of
        # churning the registry query after query
        from ..ops.jitcache import _TimedEntry, program_signature
        label = getattr(fn, "__qualname__", None) \
            or getattr(fn, "__name__", "fn")
        code = getattr(fn, "__code__", None)
        site = ((code.co_filename, code.co_firstlineno)
                if code is not None else id(fn))
        donate = tuple(donate)
        # cross-query reuse: when the closure's captured state is
        # provably value-stable, the SAME jitted program serves every
        # query with this shape — warm queries skip the re-trace that
        # used to dominate their dispatch wall (jax.jit's own trace
        # cache keys on the function OBJECT, so rebuilding the object
        # per query forfeited it)
        sig = program_signature(fn)
        cache_key = None
        entry = None
        if sig is not None:
            cache_key = (site, sig, in_specs, out_specs, donate,
                         self.axis, tuple(self.mesh.devices.flat))
            with _PROGRAM_CACHE_LOCK:
                entry = _PROGRAM_CACHE.get(cache_key)
                if entry is not None:
                    _PROGRAM_CACHE.move_to_end(cache_key)
            (_PROGRAM_HITS if entry is not None
             else _PROGRAM_MISSES).inc()
        if entry is None:
            entry = _TimedEntry(
                f"smap:{label.split('.<locals>.')[-1]}",
                jax.jit(shard_map(
                    fn, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, **{_SHARD_MAP_CHECK_KW: False}),
                    donate_argnums=donate),
                (site, in_specs, out_specs, donate), donate=donate)
            if cache_key is not None:
                with _PROGRAM_CACHE_LOCK:
                    _PROGRAM_CACHE[cache_key] = entry
                    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_CAP:
                        _PROGRAM_CACHE.popitem(last=False)
        # flight recorder: each dispatch is one round record (kind
        # "dispatch" -> dispatch_overhead; "repartition" for exchange
        # fns; None when the caller brackets the call in _sync_record —
        # every variant still counts in mesh_dispatches_total)
        return _FlightDispatch(entry, flight_kind, stage=stage,
                               rounds=rounds)

    def _shard_live_max(self, batch: Batch) -> int:
        """Max live rows on any shard (host sync) — sizes compactions."""
        per = self._smap(
            lambda b: jnp.sum(b.row_mask, keepdims=True).astype(jnp.int64), 1,
            flight_kind=None)
        _drain_inputs(batch)
        with _sync_record("shard-live-max"):
            counts = np.asarray(jax.device_get(per(batch)))
        return int(counts.max()) if counts.size else 0

    def _replicate_device(self, batch: Batch) -> Batch:
        """Re-shard a row-sharded batch to fully-replicated WITHOUT a host
        round trip: jit identity with replicated output sharding makes XLA
        insert the all-gather over ICI (the FIXED_BROADCAST exchange,
        reference operator/ExchangeClient.java pulling a broadcast buffer —
        here device-to-device only)."""
        fn = self._replicate_jit
        if fn is None:
            from ..ops.jitcache import _TimedEntry
            fn = self._replicate_jit = _FlightDispatch(_TimedEntry(
                "replicate_device",
                jax.jit(lambda b: b, out_shardings=self._replicated)),
                "dispatch", stage="exchange")
        return fn(batch)

    def _repartitioner(self, key_cols: Sequence[int],
                       pmap: Optional[_PartitionMap] = None,
                       adaptive: bool = True,
                       fused: Optional[bool] = None) -> _Repartitioner:
        """An adaptive quota-compacted hash exchange (see
        :class:`_Repartitioner`). Pass one shared ``pmap`` for every
        exchange whose outputs must colocate (both sides of a
        partitioned join); single-shot exchanges get their own map.
        ``fused=False`` forces the classic counts-then-ship plane (a
        caller shipping a huge batch once may prefer the tight quota
        over saving one sync)."""
        if pmap is None:
            pmap = _PartitionMap(self.n, adaptive=adaptive)
        return _Repartitioner(self, key_cols, pmap, fused=fused)

    # -- scan: split placement ------------------------------------------------
    def _TableScanNode(self, node: TableScanNode) -> Iterator[Batch]:
        """Round-robin split streams across shards THROUGH the device
        scan cache + async prefetch pipeline (exec/scancache.py): each
        shard's stream is a cached ``scan_splits`` pipeline, so hot
        split data replays device-resident across mesh queries instead
        of re-decoding per query, cold splits decode/stage on
        background threads ahead of the mesh program, and hits/misses
        land on the same ``scan_cache_*`` observables as the local
        path. Per-round shard chunks stack into one globally-sharded
        batch — device-to-device when every chunk is resident
        (_assemble's composed path), through the host otherwise."""
        import time as _time

        from . import scancache

        conn = self.session.catalogs.get(node.catalog)
        opts = scancache.options_from_session(self.session)
        splits = conn.split_manager.splits(node.table, self.n)
        pushdown = node.pushdown or None
        t_query0 = _time.perf_counter()

        def record_for(shard: int):
            def record_split(i: int, t0: float, batches: int) -> None:
                if self.stats is not None:
                    self.stats.record_split(
                        node.table.table, shard, t0 - t_query0,
                        _time.perf_counter() - t0, batches)
            return record_split

        streams: List[Iterator[Batch]] = [
            scancache.scan_splits(
                conn, node.catalog, list(node.columns), [s],
                lambda: pushdown, self.rows_per_batch, opts,
                record_split=record_for(i),
                check_cancel=self._check_cancel, stats=self.stats,
                static_pushdown=pushdown)
            for i, s in enumerate(splits)
        ]
        while len(streams) < self.n:
            streams.append(iter(()))
        done = [False] * self.n
        while not all(done):
            fl = _flight.current_flight()
            t0 = _time.perf_counter()
            s0 = fl.kind_wall("stall") if fl is not None else 0.0
            parts: List[Optional[Batch]] = []
            for i, st in enumerate(streams):
                if done[i]:
                    parts.append(None)
                    continue
                try:
                    parts.append(next(st))
                except StopIteration:
                    done[i] = True
                    parts.append(None)
            if all(p is None for p in parts):
                break
            if fl is not None:
                # host scan work feeding the mesh: the pull wall minus
                # the prefetch stalls recorded INSIDE the pulls (those
                # already landed in the stall bucket)
                dt = (_time.perf_counter() - t0
                      - (fl.kind_wall("stall") - s0))
                fl.record("staging", wall=max(dt, 0.0))
            yield self._assemble(parts, _plan_schema(node))

    def _assemble_resident(self, parts: List[Optional[Batch]],
                           schema: Schema, cap: int) -> Optional[Batch]:
        """Stack per-shard device chunks into one globally-sharded batch
        WITHOUT a host round trip: pad each chunk to the round's bucket
        on device, copy it device-to-device onto its shard, and compose
        the global array from the per-shard pieces
        (jax.make_array_from_single_device_arrays). Returns None — and
        the caller falls back to host staging (_stage_parts) — when
        shards disagree on a dictionary (vocab merge needs the host) or
        the backend refuses the composition."""
        compose = getattr(jax, "make_array_from_single_device_arrays",
                          None)
        if compose is None:
            return None
        ncols = len(schema)
        vocabs: List[Optional[Tuple[str, ...]]] = []
        for ci in range(ncols):
            vs = {p.columns[ci].dictionary for p in parts
                  if p is not None
                  and p.columns[ci].dictionary is not None}
            if len(vs) > 1:
                return None
            vocabs.append(next(iter(vs)) if vs
                          else (() if schema.types[ci].is_string
                                else None))
        from ..ops.jitcache import pad_capacity_jit
        devs = list(self.mesh.devices.flat)
        padded: List[Optional[Batch]] = []
        for i in range(self.n):
            p = parts[i] if i < len(parts) else None
            if p is not None and p.capacity < cap:
                p = pad_capacity_jit(p, cap)
            padded.append(p)
        try:
            def compose_col(ci: int, which: str):
                proto = next(getattr(p.columns[ci], which)
                             for p in padded if p is not None)
                shards = []
                for i, p in enumerate(padded):
                    a = (getattr(p.columns[ci], which)
                         if p is not None
                         else jnp.zeros(proto.shape, proto.dtype))
                    shards.append(jax.device_put(a, devs[i]))
                shape = (self.n * cap,) + tuple(proto.shape[1:])
                return compose(shape, self._row_sharding, shards)

            cols = [Column(schema.types[ci], compose_col(ci, "data"),
                           compose_col(ci, "validity"), vocabs[ci])
                    for ci in range(ncols)]
            mask = compose(
                (self.n * cap,), self._row_sharding,
                [jax.device_put(
                    p.row_mask if p is not None
                    else jnp.zeros((cap,), dtype=bool), devs[i])
                 for i, p in enumerate(padded)])
            return Batch(schema, cols, mask)
        except Exception:
            return None          # any residency surprise: host staging

    def _assemble(self, parts: List[Optional[Batch]],
                  schema: Schema) -> Batch:
        """Stack per-shard batches into one globally-sharded batch —
        device-resident when possible, staged through the host when a
        vocab merge or backend limitation forces it."""
        cap = max(p.capacity for p in parts if p is not None)
        resident = self._assemble_resident(parts, schema, cap)
        if resident is not None:
            return resident
        ncols = len(schema)
        datas: List[List[np.ndarray]] = [[] for _ in range(ncols)]
        valids: List[List[np.ndarray]] = [[] for _ in range(ncols)]
        masks: List[np.ndarray] = []
        vocabs: List[Optional[Tuple[str, ...]]] = [None] * ncols
        self._stage_parts(parts, schema, cap, datas, valids,
                          masks, vocabs)
        cols = []
        for ci in range(ncols):
            data = np.concatenate(datas[ci])
            valid = np.concatenate(valids[ci])
            cols.append(Column(
                schema.types[ci],
                jax.device_put(data, self._row_sharding),
                jax.device_put(valid, self._row_sharding),
                vocabs[ci]))
        mask = jax.device_put(np.concatenate(masks), self._row_sharding)
        return Batch(schema, cols, mask)

    def _stage_parts(self, parts, schema: Schema, cap: int,
                     datas, valids, masks, vocabs) -> None:
        """Fetch every shard's columns to the host (explicit
        device_get: staging deliberately rounds through the host to
        stack per-shard chunks — one device-sync span brackets the whole round so the stall is observable)."""
        ncols = len(schema)
        fl = _flight.current_flight()
        t0 = time.perf_counter()
        with TRACER.span("device-sync", what="scan-stage"):
            for p in parts:
                if p is None:
                    for ci in range(ncols):
                        dt = schema.types[ci].storage_dtype
                        datas[ci].append(np.zeros(cap, dtype=np.dtype(dt)))
                        valids[ci].append(np.zeros(cap, dtype=bool))
                    masks.append(np.zeros(cap, dtype=bool))
                    continue
                from ..batch import unify_dictionaries
                for ci, c in enumerate(p.columns):
                    d = np.asarray(jax.device_get(c.data))
                    v = np.asarray(jax.device_get(c.validity))
                    if c.dictionary is not None:
                        if vocabs[ci] is None:
                            vocabs[ci] = c.dictionary
                        elif vocabs[ci] != c.dictionary:
                            # remap codes into the accumulated vocabulary
                            merged, remaps = unify_dictionaries([
                                _host_col(c.type, vocabs[ci]),
                                c])
                            vocabs[ci] = merged
                            # remap previously collected shards
                            prev_map = remaps[0]
                            datas[ci] = [
                                _apply_remap(a, prev_map) for a in datas[ci]]
                            d = _apply_remap(d, remaps[1])
                    pad = cap - d.shape[0]
                    if pad:
                        d = np.pad(d, (0, pad))
                        v = np.pad(v, (0, pad))
                    datas[ci].append(d)
                    valids[ci].append(v)
                m = np.asarray(jax.device_get(p.row_mask))
                if cap - m.shape[0]:
                    m = np.pad(m, (0, cap - m.shape[0]))
                masks.append(m)
        if fl is not None:
            loads = [int(m.sum()) for m in masks]
            nbytes = (sum(a.nbytes for lst in datas for a in lst)
                      + sum(a.nbytes for lst in valids for a in lst)
                      + sum(m.nbytes for m in masks))
            fl.record("staging", wall=time.perf_counter() - t0,
                      rows=sum(loads), nbytes=nbytes, loads=loads)

    def _ValuesNode(self, node: ValuesNode) -> Iterator[Batch]:
        for b in super()._ValuesNode(node):
            yield self._pad_shardable(b)

    def _pad_shardable(self, b: Batch) -> Batch:
        cap = b.capacity
        per = -(-cap // self.n)
        if per * self.n != cap:
            b = concat_batches([b], capacity=per * self.n)
        return self._shard_rows(b)

    # -- aggregation: partial -> hash exchange -> final -----------------------
    def _AggregationNode(self, node: AggregationNode) -> Iterator[Batch]:
        for a in node.aggs:
            if a.distinct:
                raise NotImplementedError(
                    "DISTINCT aggregates must be lowered by the planner")
        aggs = [AggSpec(a.fn, a.arg, a.output_type, a.name, mask=a.mask,
                        param=a.param)
                for a in node.aggs]
        group = list(node.group_indices)
        from ..ops.aggregation import percentile_drains
        # final-step nodes consume STATE columns whose layout the raw
        # agg input indices don't describe — never re-check them
        if node.step != "final" and \
                percentile_drains(aggs, _plan_schema(node.child).types,
                                  bool(group)):
            # approx_percentile: colocate each group's raw rows via hash
            # exchange, then one exact segmented-sort pass per shard (no
            # mergeable state exists — the window-node pattern)
            b = self._drain(node.child)
            if b is None:
                if group:
                    return
                b = self._pad_shardable(Batch.from_arrays(
                    _plan_schema(node.child),
                    [[] for _ in node.child.fields], num_rows=0))
            if group:
                b = self._repartitioner(group, fused=False)(b)
                fn = self._smap(
                    lambda x: grouped_aggregate(x, group, aggs,
                                                mode="single"), 1, stage="agg")
                yield fn(b)
            else:
                fn = self._smap(
                    lambda x: global_aggregate(
                        _gathered(x, self.axis), aggs, mode="single"), 1, stage="agg")
                yield _keep_first_shard(fn(b), self.n)
            return
        if not group:
            yield self._global_agg(node, aggs)
            return
        key_idx = list(range(len(group)))
        allow_dense = bool_property(self.session, "dense_grouping", True)
        kb = tuple(node.key_bounds) if node.key_bounds else None
        # fragment steps (the optimizer's eager-aggregation rewrite
        # pre-splits some aggregations): PARTIAL consumes raw rows and
        # yields shard-local state, FINAL consumes state rows, SINGLE
        # does both — same kernels, different sides of the state
        # boundary (mirrors exec/local.py _AggregationNode)
        step = node.step

        partial_fn = self._smap(
            lambda b: grouped_aggregate(b, group, aggs, mode="partial",
                                        key_bounds=kb,
                                        allow_dense=allow_dense), 1, stage="agg")
        merge_fn = None

        state: Optional[Batch] = None
        fused_state = False
        src: Iterator[Batch] = iter(self.run(node.child))
        if self.fused_exchange and allow_dense and step != "final":
            # fused control plane: drain chunks through multi-round
            # lax.fori_loop wave programs (one dispatch per wave, donated
            # carry, zero mid-stage syncs). Falls back to the classic
            # per-chunk loop below for whatever the drain did not take
            # (gate failed, or the wave signature changed mid-stream).
            state, src = self._fused_agg_drain(src, group, aggs, kb)
            fused_state = state is not None
        merges = 0
        next_check = 1
        check_every = 1
        for chunk in src:
            if kb is not None and allow_dense and step != "final":
                # sharded batches reduce to one replicated scalar; the
                # flag joins the query's single end-of-run error sync.
                # UNCONDITIONAL on this tier: per-shard dispatch depends
                # on post-exchange quota capacities the host can't
                # mirror, so bounds are enforced as hard invariants —
                # an overclaimed bound fails LOUDLY here rather than
                # risking a silent clamp in a later merge/final shard
                from ..ops.jitcache import key_bounds_violation_jit
                self.error_flags.append(
                    key_bounds_violation_jit(chunk, group, kb))
            partial = (chunk if step == "final" else partial_fn(chunk))
            if state is None:
                state = partial
            else:
                if merge_fn is None:
                    merge_fn = self._smap(
                        lambda a, b: grouped_aggregate(
                            concat_batches([a, b]), key_idx, aggs,
                            mode="merge", key_bounds=kb,
                            allow_dense=allow_dense), 2, stage="agg")
                merged = merge_fn(state, partial)
                merges += 1
                # compaction sizing is an optimization, never a
                # correctness gate (skipping a check only retains a
                # larger capacity for longer), so the live-max host
                # sync runs on a doubling cadence — first merge, back
                # off while nothing compacts, snap back when one does
                # (the local executor's adaptive sparse-check idiom)
                if merges >= next_check:
                    live = self._shard_live_max(merged)
                    cap = bucket_capacity(max(live, 1))
                    if cap * self.n < merged.capacity:
                        compact_fn = self._smap(
                            lambda b, _cap=cap: b.compact(_cap, check=False),
                            1, stage="agg")
                        merged = compact_fn(merged)
                        check_every = 1
                    else:
                        check_every = min(check_every * 2, 8)
                    next_check = merges + check_every
                state = merged
        if state is None:
            if node.default_gids and step in ("single", "final"):
                # grouping sets over empty input: synthesize the empty
                # sets' grand-total rows (see local._default_grouping_batch)
                from .local import _default_grouping_batch
                yield self._pad_shardable(_default_grouping_batch(node))
            return
        if step == "partial":
            # states stay shard-local: the downstream FINAL node owns
            # the hash exchange that co-locates groups
            yield state
            return
        if fused_state and state.capacity <= _FUSED_GATHER_SLOTS:
            # fused finisher: the carry's proven capacity is small
            # enough to all-gather, so the final runs replicated in ONE
            # dispatch — no exchange round at all. Output identical on
            # every shard; mask all but shard 0 (the _global_agg form)
            final_fn = self._smap(
                lambda b, _ax=self.axis: grouped_aggregate(
                    _gathered(b, _ax), key_idx, aggs, mode="final",
                    key_bounds=kb, allow_dense=allow_dense), 1,
                stage="agg")
            out = _keep_first_shard(final_fn(state), self.n)
        else:
            state = self._repartitioner(key_idx, fused=False)(state)
            final_fn = self._smap(
                lambda b: grouped_aggregate(b, key_idx, aggs, mode="final",
                                            key_bounds=kb,
                                            allow_dense=allow_dense), 1,
                stage="agg")
            out = final_fn(state)
        if node.default_gids and step in ("single", "final") \
                and out.host_count() == 0:
            from .local import _default_grouping_batch
            yield self._pad_shardable(_default_grouping_batch(node))
            return
        yield out

    @staticmethod
    def _wave_sig(b: Batch):
        """Trace signature a fused wave must hold constant: chunks are
        tree-stacked into ONE program, so capacity, schema and every
        column's dictionary object must match the wave's first chunk."""
        return (b.capacity, b.schema,
                tuple(id(c.dictionary) for c in b.columns))

    def _fused_agg_drain(self, src: Iterator[Batch], group: List[int],
                         aggs: List[AggSpec], kb):
        """Drain grouped-aggregation input through fused multi-round wave
        programs (tentpole tier A).

        Each wave stacks up to ``mesh_fused_loop_rounds`` chunks into ONE
        shard_map program whose body is a ``lax.fori_loop`` of
        partial-aggregate + state-merge at a STATIC state capacity proven
        from the dense key domain (dictionary vocab / bool / stats
        bounds — the PR 2/PR 10 machinery). The host dispatches once per
        wave instead of 3-4 times (+ a liveness sync) per chunk; the
        previous wave's carry is DONATED into the next wave's program so
        round-carried state stops churning buffers. Bounds violations
        fold into a replicated scalar that joins the query's single
        end-of-run error sync.

        Returns ``(state, leftover)``: the fused carry (None when the
        gate rejected the stream) and an iterator of chunks the caller's
        classic loop must still process."""
        from ..ops.aggregation import (dense_group_plan, has_drain_agg,
                                       _wide_state_aggs)
        first = next(src, None)
        if first is None:
            return None, iter(())
        if has_drain_agg(aggs) or _wide_state_aggs(aggs):
            # drain/wide states don't take the dense path in-program;
            # without it no static carry capacity can be proven
            return None, itertools.chain([first], src)
        kb_list = list(kb) if kb else None
        plan = dense_group_plan(first, group, _FUSED_STATE_SLOTS, kb_list)
        if plan is None:
            return None, itertools.chain([first], src)
        cap_out = bucket_capacity(plan.K + 1)
        key_idx = list(range(len(group)))
        sig0 = self._wave_sig(first)
        wave_fns: Dict[Tuple[int, bool], object] = {}

        def run_wave(carry: Optional[Batch],
                     chunks: List[Batch]) -> Batch:
            rounds = 1 << max(len(chunks) - 1, 0).bit_length()
            if rounds > len(chunks):
                # pad to a power of two so wave programs stay few: dead
                # copies of the last chunk (mask off -> overflow slot)
                dead = Batch(chunks[-1].schema, chunks[-1].columns,
                             jnp.zeros_like(chunks[-1].row_mask))
                chunks = chunks + [dead] * (rounds - len(chunks))
            has_carry = carry is not None
            fn = wave_fns.get((rounds, has_carry))
            if fn is None:
                fn = wave_fns[(rounds, has_carry)] = self._smap(
                    _fused_agg_wave_fn(group, key_idx, aggs, kb,
                                       cap_out, has_carry, self.axis),
                    rounds + (1 if has_carry else 0),
                    n_out=2, replicated_out=(1,), stage="agg",
                    donate=(0,) if has_carry else (),
                    rounds=rounds)
            out, viol = fn(*([carry] if has_carry else []), *chunks)
            if kb is not None:
                self.error_flags.append(viol)
            return out

        state: Optional[Batch] = None
        pending = [first]
        leftover: Optional[Batch] = None
        for chunk in src:
            if self._wave_sig(chunk) != sig0:
                # signature drifted (dictionary / capacity change): hand
                # the rest back to the classic per-chunk plane, which
                # merges into the fused carry via concat-remap
                leftover = chunk
                break
            pending.append(chunk)
            if len(pending) >= self.fused_loop_rounds:
                state = run_wave(state, pending)
                pending = []
        if pending:
            state = run_wave(state, pending)
        if leftover is not None:
            return state, itertools.chain([leftover], src)
        return state, iter(())

    def _global_agg(self, node: AggregationNode,
                    aggs: List[AggSpec]) -> Batch:
        step = node.step
        partial_fn = self._smap(
            lambda b: global_aggregate(b, aggs, mode="partial"), 1, stage="agg")
        merge_fn = self._smap(
            lambda a, b: global_aggregate(
                concat_batches([a, b]), aggs, mode="merge"), 2, stage="agg")
        state: Optional[Batch] = None
        for chunk in self.run(node.child):
            partial = (chunk if step == "final" else partial_fn(chunk))
            state = partial if state is None else merge_fn(state, partial)
        if state is None:
            empty = Batch.from_arrays(
                _plan_schema(node.child),
                [[] for _ in node.child.fields], num_rows=0)
            state = partial_fn(self._pad_shardable(empty))
        if step == "partial":
            return state          # shard-local states; FINAL gathers
        # gather every shard's state and finalize replicated
        final_fn = self._smap(
            lambda b: global_aggregate(
                _gathered(b, self.axis), aggs, mode="final"), 1, stage="agg")
        out = final_fn(state)
        # output is identical on every shard; mask all but shard 0
        return _keep_first_shard(out, self.n)

    # -- joins -----------------------------------------------------------------
    def _JoinNode(self, node: JoinNode) -> Iterator[Batch]:
        build = self._drain(node.right)
        if node.join_type == "cross":
            yield from self._cross_join(node, build)
            return
        residual = (self._resolve(node.residual)
                    if node.residual is not None else None)
        # plain (unchecked) filter: it runs INSIDE the shard_map'd probe
        # step, where a host-side error collector would leak tracers; a
        # residual row error here degrades to dropped-row semantics
        residual_fn = (compile_filter(residual, _plan_schema(node))
                       if residual is not None else None)
        residual_outer = (residual_fn is not None
                          and node.join_type in ("left", "full"))
        payload = list(range(len(node.right.fields)))
        payload_names = [f"$b{i}" for i in payload]
        out_schema = _plan_schema(node)

        if build is None:
            for probe in self.run(node.left):
                if node.join_type in ("left", "full"):
                    yield self._null_extend(probe, node)
            return

        lkeys, rkeys = list(node.left_keys), list(node.right_keys)
        replicated = node.distribution == "replicated"
        track_full = node.join_type == "full"
        pmap = repart_build = None
        if replicated:
            # FIXED_BROADCAST: build side replicated to every shard —
            # device-to-device all-gather, no host staging
            build_side = self._replicate_device(build)
        else:
            # FIXED_HASH: build repartitioned by join key over ICI once.
            # ONE _PartitionMap covers build AND probe exchanges, so
            # equal keys colocate under any (re-balanced) assignment.
            # FULL joins pin the map (adaptive=False): their per-shard
            # unmatched-build masks cannot survive rows moving shards.
            pmap = _PartitionMap(self.n, adaptive=not track_full)
            repart_build = self._repartitioner(rkeys, pmap)
            e0 = pmap.epoch
            build_side = repart_build(build)
            # fused plane: fold the build round's counts NOW (one sync,
            # before any probe ships) so a skewed build re-balances the
            # shared map before the probe stream commits to it. The
            # fused ship ran BEFORE its counts were seen, so a verdict
            # from its own round means the build itself sits under the
            # stale assignment — re-ship it once
            repart_build.observe_pending()
            if pmap.epoch != e0:
                build_side = repart_build.replay(build)

        # prepare the build ONCE per shard (the LookupSource role, same
        # contract as exec/local.py): every probe program takes the
        # prepared pytree instead of re-sorting the build per probe
        # batch. Planner key_bounds (stats-driven strategy selection)
        # build the mixed-radix direct-address table; the build is
        # cross-checked against the promised bounds through the
        # row-error channel before any probe runs.
        from ..ops.join import (direct_keyed_plan, prepare_build,
                                prepare_direct_keyed)
        from ..ops.jitcache import key_bounds_violation_jit
        from .local import _note_join_strategy, bool_property
        kb_plan = (direct_keyed_plan(tuple(node.key_bounds))
                   if node.key_bounds
                   and bool_property(self.session, "join_dense_path",
                                     True) else None)
        if kb_plan is not None:
            los, sizes, K = kb_plan
            cap = bucket_capacity(K)

            def prep_local(b: Batch):
                return prepare_direct_keyed(b, rkeys, los, sizes, cap)
            # GSPMD reduces the sharded violation scan to one scalar;
            # it joins the query's single end-of-run error sync
            self.error_flags.append(key_bounds_violation_jit(
                build, tuple(rkeys), tuple(node.key_bounds)))
        else:
            def prep_local(b: Batch):
                return prepare_build(b, rkeys)
        prep_in = (0,) if replicated else ()
        prep_smap = self._smap(prep_local, 1, replicated_in=prep_in,
                               replicated_out=replicated, stage="join")
        prepared = prep_smap(build_side)
        _note_join_strategy(
            self.stats, node,
            ("direct" if kb_plan is not None else "sorted")
            if node.build_unique else "expand", node.distribution)
        # probe programs: build + prepared ride the same sharding
        rep_in2 = (1, 2) if replicated else ()

        # FULL OUTER probes like LEFT; the unmatched-build tail is emitted
        # after the probe stream (per shard — the optimizer forces
        # partitioned distribution, so each build row lives on one shard)
        jt = "left" if node.join_type == "full" else node.join_type

        npro = len(node.left.fields)

        def local_probe(probe_l: Batch, build_l: Batch, prep_l,
                        maxk: int) -> Batch:
            if node.build_unique:
                out = lookup_join(probe_l, build_l, lkeys, rkeys,
                                  payload, payload_names, jt,
                                  prepared=prep_l)
            else:
                out = expand_join(probe_l, build_l, lkeys, rkeys,
                                  payload, payload_names, jt,
                                  max_matches=maxk, prepared=prep_l)
            out = Batch(out_schema, out.columns, out.row_mask)
            return residual_fn(out) if residual_fn else out

        def local_probe_outer(probe_l: Batch, build_l: Batch, prep_l,
                              maxk: int):
            """LEFT/FULL with a residual, shard-local (same contract as
            the local executor's _probe_outer_residual: residual gates
            matches, probe rows never drop; returns (batch,
            surviving-build-match mask) — the mask feeds the FULL
            unmatched-build tail)."""
            from ..ops.join import (expand_match_origins, semi_join_mask,
                                    unique_match_build_mask)
            if node.build_unique:
                out = lookup_join(probe_l, build_l, lkeys, rkeys,
                                  payload, payload_names, "left",
                                  prepared=prep_l)
                match = semi_join_mask(probe_l, build_l, lkeys, rkeys,
                                       prepared=prep_l)
                gated = residual_fn(Batch(out_schema, out.columns,
                                          probe_l.row_mask & match))
                survived = gated.row_mask
                cols = list(out.columns[:npro])
                for c in out.columns[npro:]:
                    cols.append(Column(c.type, c.data,
                                       c.validity & survived,
                                       c.dictionary))
                bmask = (unique_match_build_mask(
                    probe_l, build_l, lkeys, rkeys, survived,
                    prepared=prep_l)
                    if track_full
                    else jnp.zeros(build_l.capacity, dtype=bool))
                return Batch(out_schema, cols, probe_l.row_mask), bmask
            k = max(1, maxk)
            e = expand_join(probe_l, build_l, lkeys, rkeys, payload,
                            payload_names, "inner", max_matches=k,
                            prepared=prep_l)
            gated = residual_fn(Batch(out_schema, e.columns,
                                      e.row_mask))
            survived = gated.row_mask
            C = probe_l.capacity
            has = jnp.any(survived.reshape(k, C), axis=0)
            # reinstate unmatched probe rows in their slot-0 lanes with
            # null payload (lane = slot*C + i, so slot 0 is the first C)
            reinstate = jnp.zeros(k * C, dtype=bool).at[:C].set(
                probe_l.row_mask & ~has)
            cols = []
            for i, c in enumerate(e.columns):
                if i < npro:
                    cols.append(c)
                else:
                    cols.append(Column(c.type, c.data,
                                       c.validity & survived,
                                       c.dictionary))
            if track_full:
                orig, _ = expand_match_origins(probe_l, build_l, lkeys,
                                               rkeys, k,
                                               prepared=prep_l)
                n = build_l.capacity
                bmask = jnp.zeros(n, dtype=bool).at[
                    jnp.where(survived, orig, n)].max(survived,
                                                      mode="drop")
            else:
                bmask = jnp.zeros(build_l.capacity, dtype=bool)
            return Batch(out_schema, cols,
                         survived | reinstate), bmask

        count_fn = None
        maxk_static: Optional[int] = None
        if not node.build_unique:
            # ONE build-side multiplicity readback bounds every probe
            # batch's match count (mirrors exec/local.py): the per-probe-
            # batch count sync only returns for skewed builds, where the
            # bound would oversize every batch's expansion
            from ..ops.join import max_multiplicity
            mult_fn = self._smap(
                lambda pr: max_multiplicity(pr)[None].astype(jnp.int64),
                1, replicated_in=(0,) if replicated else (),
                flight_kind=None, stage="join")
            _drain_inputs(prepared)
            with _sync_record("join-multiplicity"):
                bound = int(np.asarray(
                    jax.device_get(mult_fn(prepared))).max())
            if bound <= self.SKEW_MATCH_LIMIT:
                # the bound survives re-assignment: a key's rows move
                # between shards ATOMICALLY (bucket granularity), so a
                # shard's max per-key multiplicity never exceeds the
                # global max this readback saw
                maxk_static = bucket_capacity(max(bound, 1), minimum=1)
            else:
                def local_count(p: Batch, b: Batch, pr) -> jnp.ndarray:
                    return match_count_max(p, b, lkeys, rkeys,
                                           prepared=pr)[None]
                count_fn = self._smap(local_count, 3,
                                      replicated_in=rep_in2,
                                      flight_kind=None, stage="join")

        repart_probe = (None if replicated
                        else self._repartitioner(lkeys, pmap))
        join_fns: Dict[int, object] = {}
        match_fn = (self._smap(
            lambda p, b, pr: build_match_mask(p, b, lkeys, rkeys,
                                              prepared=pr), 3,
            replicated_in=rep_in2, stage="join")
            if track_full else None)
        build_matched = None
        built_epoch = pmap.epoch if pmap is not None else 0
        # fused probe plane (tentpole tier B): when the match bound is
        # static (no per-batch count sync) and no outer/residual bookkeeping
        # rides along, the key exchange FUSES into the probe program —
        # repartition collectives and probe compute are one dispatch, with
        # the round's bucket counts as a device-resident second output that
        # the deferred skew check folds in without blocking the stream
        fuse_probe = (repart_probe is not None and repart_probe.fused
                      and count_fn is None and not residual_outer
                      and not track_full)
        fused_probe_fns: Dict[Tuple, object] = {}
        for probe in self.run(node.left):
            if fuse_probe:
                if pmap.epoch != built_epoch:
                    # deferred skew verdict landed: loop-exit-and-rebuild —
                    # re-ship the retained build under the new assignment
                    # before the next fused round commits to it
                    build_side = repart_build.replay(build)
                    prepared = prep_smap(build_side)
                    built_epoch = pmap.epoch
                from .failpoints import FAILPOINTS
                fl = _flight.current_flight()
                t0 = time.perf_counter()
                FAILPOINTS.hit("mesh.repartition")
                maxk = maxk_static if maxk_static is not None else 1
                key = (pmap.assign, repart_probe.fused_quota(probe), maxk)
                fn = fused_probe_fns.get(key)
                if fn is None:
                    from ..parallel.exchange import repartition_fused
                    _a, _q, _k = key
                    _ax, _n = self.axis, self.n

                    def fused_probe(p, b, pr, _a=_a, _q=_q, _k=_k):
                        shipped, counts = repartition_fused(
                            p, lkeys, _ax, _n, _a, _q)
                        return local_probe(shipped, b, pr, _k), counts
                    fn = fused_probe_fns[key] = self._smap(
                        fused_probe, 3, replicated_in=rep_in2, n_out=2,
                        flight_kind=None, stage="join")
                out, counts = fn(probe, build_side, prepared)
                repart_probe.note_counts(counts)
                if fl is not None:
                    # exchange + probe are ONE program now: the round
                    # record is a repartition record whose wall covers
                    # the whole fused dispatch
                    fl.record("repartition",
                              wall=time.perf_counter() - t0)
                yield out
                continue
            if repart_probe is not None:
                probe = repart_probe(probe)
                if pmap.epoch != built_epoch:
                    # adaptive re-split (StageMonitor's skew verdict in
                    # action): a hot bucket moved shards, so the
                    # prepared build is stale — re-ship the retained
                    # build under the new assignment and re-prepare,
                    # once per epoch, before the next probe batch
                    build_side = repart_build.replay(build)
                    prepared = prep_smap(build_side)
                    built_epoch = pmap.epoch
            maxk = 1
            if maxk_static is not None:
                maxk = maxk_static
            elif count_fn is not None:
                _drain_inputs(probe, build_side, prepared)
                with _sync_record("join-match-count"):
                    maxk = bucket_capacity(
                        max(int(np.asarray(jax.device_get(
                            count_fn(probe, build_side,
                                     prepared))).max()), 1),
                        minimum=1)
            fn = join_fns.get(maxk)
            if fn is None:
                if residual_outer:
                    fn = join_fns[maxk] = self._smap(
                        lambda p, b, pr, _k=maxk: local_probe_outer(
                            p, b, pr, _k),
                        3, replicated_in=rep_in2, stage="join")
                else:
                    fn = join_fns[maxk] = self._smap(
                        lambda p, b, pr, _k=maxk: local_probe(
                            p, b, pr, _k), 3,
                        replicated_in=rep_in2, stage="join")
            if residual_outer:
                out, m = fn(probe, build_side, prepared)
                if track_full:
                    build_matched = (m if build_matched is None
                                     else build_matched | m)
                yield out
                continue
            if track_full:
                m = match_fn(probe, build_side, prepared)
                build_matched = (m if build_matched is None
                                 else build_matched | m)
            yield fn(probe, build_side, prepared)
        if repart_probe is not None:
            # per-stage control-scalar fetch: any still-pending fused
            # round counts fold into the shared map exactly once here,
            # so skew stats never silently drop at stage end
            repart_probe.observe_pending()
        if track_full:
            left_fields = node.left.fields

            def local_tail(b_l: Batch, matched_l) -> Batch:
                mask = b_l.row_mask & ~matched_l
                novalid = jnp.zeros(b_l.capacity, dtype=bool)
                cols = [Column(f.type,
                               jnp.zeros(b_l.capacity,
                                         dtype=f.type.storage_dtype),
                               novalid, () if f.type.is_string else None)
                        for f in left_fields]
                cols.extend(b_l.columns)
                return Batch(out_schema, cols, mask)

            if build_matched is None:
                build_matched = jnp.zeros_like(build_side.row_mask)
            yield self._smap(local_tail, 2, stage="join")(build_side, build_matched)

    def _SemiJoinNode(self, node: SemiJoinNode) -> Iterator[Batch]:
        build = self._drain(node.filtering)
        skeys, fkeys = list(node.source_keys), list(node.filtering_keys)
        neg = node.negated
        if build is None:
            for b in self.run(node.source):
                if neg:
                    yield b
            return
        # stats-driven distribution (optimizer._attach_join_strategy):
        # a large filtering set hash-partitions BOTH sides by key so
        # membership never broadcasts — matching keys colocate, so
        # per-shard verdicts compose exactly. NULL-aware anti joins
        # always replicate (their build_has_null/build_empty facts are
        # global) — the optimizer never marks them partitioned.
        # (mark-joins — residual semis — keep the replicated path: their
        # expansion probes are already bounded per shard)
        partitioned = (node.distribution == "partitioned"
                       and not (neg and node.null_aware)
                       and node.residual is None)
        from .local import _note_join_strategy
        pmap = repart_build = None
        if partitioned:
            # one map for both sides (see _JoinNode): verdicts compose
            # per shard under any re-balanced assignment
            pmap = _PartitionMap(self.n)
            repart_build = self._repartitioner(fkeys, pmap)
            e0 = pmap.epoch
            build_rep = repart_build(build)
            # fold the build round's counts before the source stream
            # commits to the shared assignment; re-ship once if the
            # build's own round triggered the re-split (see _JoinNode)
            repart_build.observe_pending()
            if pmap.epoch != e0:
                build_rep = repart_build.replay(build)
            repart_src = self._repartitioner(skeys, pmap)
        else:
            build_rep = self._replicate_device(build)
            repart_src = None
        # record the EXECUTED distribution: a residual mark-join the
        # planner marked partitioned still runs replicated here
        _note_join_strategy(self.stats, node, "sorted",
                            "partitioned" if partitioned
                            else "replicated")

        if node.residual is None:
            # prepare the membership table ONCE per shard (instead of
            # re-sorting the filtering side inside every probe program)
            from ..ops.join import prepare_build
            prep_smap = self._smap(lambda f: prepare_build(f, fkeys), 1,
                                   replicated_in=(0,) if not partitioned
                                   else (),
                                   replicated_out=not partitioned, stage="semi")
            prep = prep_smap(build_rep)

            def local(b: Batch, flt: Batch, pr) -> Batch:
                mask = semi_join_mask(b, flt, skeys, fkeys, negated=neg,
                                      null_aware=node.null_aware,
                                      prepared=pr)
                return Batch(b.schema, b.columns, mask)

            fn = self._smap(local, 3,
                            replicated_in=(1, 2) if not partitioned
                            else (), stage="semi")
            built_epoch = pmap.epoch if pmap is not None else 0
            # fused source plane: key exchange + membership probe as ONE
            # dispatch per round, bucket counts deferred (see _JoinNode)
            fuse_src = repart_src is not None and repart_src.fused
            fused_fns: Dict[Tuple, object] = {}
            for b in self.run(node.source):
                if fuse_src:
                    if pmap.epoch != built_epoch:
                        build_rep = repart_build.replay(build)
                        prep = prep_smap(build_rep)
                        built_epoch = pmap.epoch
                    from .failpoints import FAILPOINTS
                    fl = _flight.current_flight()
                    t0 = time.perf_counter()
                    FAILPOINTS.hit("mesh.repartition")
                    key = (pmap.assign, repart_src.fused_quota(b))
                    f2 = fused_fns.get(key)
                    if f2 is None:
                        from ..parallel.exchange import repartition_fused
                        _a, _q = key
                        _ax, _n = self.axis, self.n

                        def fused_semi(p, flt, pr, _a=_a, _q=_q):
                            shipped, counts = repartition_fused(
                                p, skeys, _ax, _n, _a, _q)
                            return local(shipped, flt, pr), counts
                        f2 = fused_fns[key] = self._smap(
                            fused_semi, 3, n_out=2, flight_kind=None,
                            stage="semi")
                    out, counts = f2(b, build_rep, prep)
                    repart_src.note_counts(counts)
                    if fl is not None:
                        fl.record("repartition",
                                  wall=time.perf_counter() - t0)
                    yield out
                    continue
                if repart_src is not None:
                    b = repart_src(b)
                    if pmap.epoch != built_epoch:
                        # adaptive re-split: re-ship + re-prepare the
                        # filtering side under the new assignment
                        build_rep = repart_build.replay(build)
                        prep = prep_smap(build_rep)
                        built_epoch = pmap.epoch
                yield fn(b, build_rep, prep)
            if repart_src is not None:
                repart_src.observe_pending()
            return

        # mark-join (EXISTS with residual): shard-local against the
        # replicated filtering side; expansion factor from ONE build-side
        # multiplicity readback (skewed builds per-chunk, as in the join)
        from .local import mark_exists_mask
        from ..ops.join import build_sorted, max_multiplicity
        mult_fn = self._smap(
            lambda f: max_multiplicity(
                build_sorted(f, fkeys))[None].astype(jnp.int64), 1,
            replicated_in=(0,), flight_kind=None, stage="semi")
        _drain_inputs(build_rep)
        with _sync_record("semi-multiplicity"):
            bound = int(np.asarray(
                jax.device_get(mult_fn(build_rep))).max())
        res_maxk = (bucket_capacity(max(bound, 1), minimum=1)
                    if bound <= self.SKEW_MATCH_LIMIT else None)
        count_fn = (None if res_maxk is not None else self._smap(
            lambda p, f: match_count_max(p, f, skeys, fkeys)[None], 2,
            replicated_in=(1,), flight_kind=None, stage="semi"))
        fns: Dict[int, object] = {}
        for b in self.run(node.source):
            if res_maxk is not None:
                maxk = res_maxk
            else:
                _drain_inputs(b, build_rep)
                with _sync_record("semi-match-count"):
                    maxk = bucket_capacity(
                        max(int(np.asarray(jax.device_get(
                            count_fn(b, build_rep))).max()), 1),
                        minimum=1)
            fn = fns.get(maxk)
            if fn is None:
                def local_mark(p: Batch, f: Batch, _k=maxk) -> Batch:
                    mask = mark_exists_mask(p, f, skeys, fkeys,
                                            node.residual, neg, _k)
                    return Batch(p.schema, p.columns, mask)
                fn = fns[maxk] = self._smap(local_mark, 2,
                                            replicated_in=(1,), stage="semi")
            yield fn(b, build_rep)

    # -- sort family: local pre-reduce + gather-merge -------------------------
    @staticmethod
    def _sort_sentinel_dt(dtype):
        if dtype == jnp.uint64:
            return jnp.iinfo(jnp.uint64).max
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.inf
        return jnp.iinfo(dtype).max

    def _SortNode(self, node: SortNode) -> Iterator[Batch]:
        b = self._drain(node.child)
        if b is None:
            return
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.keys]
        n = self.n
        samples_per_shard = 64
        # bind value-stable locals (not self) so the program fingerprints
        # for the cross-query cache; _sort_sentinel_dt is a staticmethod,
        # so the attribute access yields a plain function
        _ax = self.axis
        _sentinel_dt = self._sort_sentinel_dt

        # RANGE-partitioned distributed sort (reference dist-sort.rst +
        # MergeOperator.java:45, reshaped for SPMD): sample the primary
        # key per shard, agree on splitters via all_gather, all-to-all
        # rows into disjoint key ranges, sort shard-locally — shard-major
        # concatenation IS the global order; no host re-sort, no N-way
        # merge stream.
        def program(x: Batch) -> Batch:
            from ..ops.sort import _sortable
            from ..parallel.exchange import repartition_by_ids
            x = sort_batch(x, keys)          # local sort (dead rows last)
            k0 = keys[0]
            null_rank, data = _sortable(x.columns[k0.column], k0)
            nulls_first = k0.effective_nulls_first()
            live = x.row_mask
            nn = live & (null_rank == (1 if nulls_first else 0))
            # after the local sort, non-null live rows are contiguous
            n_nn = jnp.sum(nn.astype(jnp.int32))
            start = jnp.sum((live & ~nn).astype(jnp.int32)) \
                if nulls_first else jnp.int32(0)
            m = samples_per_shard
            step = jnp.maximum(n_nn, 1).astype(jnp.float32) / m
            pos = (start + ((jnp.arange(m, dtype=jnp.float32) + 0.5)
                            * step).astype(jnp.int32))
            pos = jnp.clip(pos, 0, x.capacity - 1)
            local_samples = jnp.take(data, pos, axis=0)
            # shards with no non-null rows contribute max-sentinels so
            # they never pull the splitters down
            sent = jnp.full((m,), _sentinel_dt(data.dtype),
                            dtype=data.dtype)
            local_samples = jnp.where(n_nn > 0, local_samples, sent)
            all_samples = jax.lax.all_gather(
                local_samples, _ax, tiled=True)       # [n*m]
            s_sorted = jax.lax.sort([all_samples])[0]
            splitters = jnp.take(
                s_sorted, jnp.arange(1, n, dtype=jnp.int32) * m, axis=0)
            pid = jnp.searchsorted(splitters, data,
                                   side="right").astype(jnp.int32)
            null_pid = jnp.int32(0 if nulls_first else n - 1)
            pid = jnp.where(nn, pid, null_pid)
            ex = repartition_by_ids(Batch(x.schema, x.columns, live),
                                    pid, _ax, n)
            return sort_batch(ex, keys)

        # shard-major concatenation of the range-partitioned shards IS the
        # global order — yield the device-resident sharded batch directly
        yield self._smap(program, 1, stage="sort")(b)

    def _TopNNode(self, node: TopNNode) -> Iterator[Batch]:
        """Shard-local top-n accumulation (collective-free per batch),
        then ONE device-side all-gather merge at the end — replaces the
        round-4 path that gathered every candidate batch to the host
        (reference TopNOperator keeps a per-driver heap the same way and
        merges once at output)."""
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.keys]
        cap = bucket_capacity(node.count)
        local_topn = self._smap(
            lambda b: top_n(b, keys, node.count).compact(cap, check=False), 1, stage="sort")
        merge_fn = self._smap(
            lambda s, c: top_n(concat_batches([s, c]), keys,
                               node.count).compact(cap, check=False), 2, stage="sort")
        state: Optional[Batch] = None
        for b in self.run(node.child):
            cand = local_topn(b)
            state = cand if state is None else merge_fn(state, cand)
        if state is not None:
            # every shard computes the same global top-n over the gathered
            # candidates; mask all but shard 0's copy
            final_fn = self._smap(
                lambda s, _ax=self.axis: sort_batch(
                    top_n(_gathered(s, _ax), keys, node.count),
                    keys), 1, stage="sort")
            yield _keep_first_shard(final_fn(state), self.n)

    def _UnnestNode(self, node) -> Iterator[Batch]:
        # shard-local expansion: every shard expands by the same static
        # element count L, so per-shard capacity stays uniform (cap_l*L)
        # and downstream exchanges keep mesh divisibility
        from .local import unnest_expand_fn, _plan_schema as _ps
        exprs = tuple(self._resolve(e) for e in node.exprs)
        fn = unnest_expand_fn(exprs, node.ordinality, _ps(node))

        def local_unnest(x: Batch):
            out, err = fn(x)
            e = (jnp.zeros((1,), jnp.int32) if err is None
                 else err.reshape(1).astype(jnp.int32))
            return out, e

        sfn = self._smap(local_unnest, 1, n_out=2)
        for b in self.run(node.child):
            out, err = sfn(b)
            self.error_flags.append(jnp.max(err))
            yield out

    def _WindowNode(self, node) -> Iterator[Batch]:
        from ..ops.window import WindowSpec, evaluate_window
        b = self._drain(node.child)
        if b is None:
            return
        specs = [WindowSpec(f.fn, f.args, f.output_type, f.name, f.offset,
                            f.ignore_order, f.frame, f.frame_start,
                            f.frame_end) for f in node.functions]
        keys = [SortKey(k.index, k.ascending, k.nulls_first)
                for k in node.order_keys]
        parts = list(node.partition_indices)
        schema = _plan_schema(node)
        if parts:
            # colocate partitions via hash exchange, evaluate shard-locally
            b = self._repartitioner(parts, fused=False)(b)
            fn = self._smap(
                lambda x: evaluate_window(x, parts, keys, specs), 1)
            out = fn(b)
        else:
            # single global partition: every shard evaluates the window
            # over the device-gathered batch (replicated compute over ICI;
            # no host round trip); keep shard 0's copy
            fn = self._smap(
                lambda x: evaluate_window(_gathered(x, self.axis),
                                          parts, keys, specs), 1)
            out = _keep_first_shard(fn(b), self.n)
        yield Batch(schema, out.columns, out.row_mask)

    def _DistinctNode(self, node: DistinctNode) -> Iterator[Batch]:
        b = self._drain(node.child)
        if b is None:
            return
        cols = list(range(len(node.fields)))
        allow_dense = bool_property(self.session, "dense_grouping", True)
        kb = tuple(node.key_bounds) if node.key_bounds else None
        if kb is not None and allow_dense:
            # unconditional hard-invariant check — see _AggregationNode
            from ..ops.jitcache import key_bounds_violation_jit
            self.error_flags.append(key_bounds_violation_jit(b, cols, kb))
        b = self._repartitioner(cols, fused=False)(b)
        fn = self._smap(
            lambda x: grouped_aggregate(x, cols, [], mode="single",
                                        key_bounds=kb,
                                        allow_dense=allow_dense), 1)
        yield fn(b)

    def _MarkDistinctNode(self, node) -> Iterator[Batch]:
        """Colocate rows by the distinct tuple, then flag shard-locally:
        equal tuples land on one shard, so first-occurrence is global."""
        import jax.numpy as jnp
        from ..ops.aggregation import mark_distinct_flags
        from .local import _plan_schema as plan_schema
        b = self._drain(node.child)
        if b is None:
            return
        b = self._repartitioner(list(node.cols), fused=False)(b)
        schema = plan_schema(node)

        def local_mark(x: Batch) -> Batch:
            flags = mark_distinct_flags(x, list(node.cols))
            from ..batch import Column
            from .. import types as T
            col = Column(T.BOOLEAN, flags, x.row_mask, None)
            return Batch(schema, list(x.columns) + [col], x.row_mask)
        yield self._smap(local_mark, 1)(b)

    def _drain(self, node: PlanNode) -> Optional[Batch]:
        batches = list(self.run(node))
        if not batches:
            return None
        if len(batches) == 1:
            return batches[0]
        # concat shard-locally to keep the result sharded
        fn = self._smap(lambda *bs: concat_batches(list(bs)), len(batches), stage="scan")
        return fn(*batches)


# -- helpers -----------------------------------------------------------------

def _fused_agg_wave_fn(group, key_idx, aggs, kb, cap_out: int,
                       has_carry: bool, axis: str):
    """One-dispatch multi-round aggregation program (DrJAX pattern:
    MapReduce rounds as traced code, not host loops). Stacks the wave's
    chunks leaf-wise, then runs a ``lax.fori_loop`` of partial-aggregate
    + state-merge whose carry rides at the STATIC ``cap_out`` capacity
    the dense key domain proves. Returns ``(state, violation)`` where
    the violation scalar is pmax-replicated so it can join the query's
    single end-of-run error sync."""
    kb_t = tuple(kb) if kb else None
    group_t = tuple(group)

    def _partial(chunk: Batch) -> Batch:
        return grouped_aggregate(chunk, group, aggs, mode="partial",
                                 output_capacity=cap_out,
                                 key_bounds=kb_t, allow_dense=True)

    def fused_agg_wave(*args):
        chunks = args[1:] if has_carry else args
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *chunks)
        if kb_t is not None:
            # bounds check over every round at once: the stacked [R, C]
            # leaves broadcast straight through the violation predicate
            from ..ops.jitcache import _bounds_violation
            viol = jax.lax.pmax(
                _bounds_violation(group_t, kb_t)(stacked), axis)
        else:
            viol = jnp.int32(0)
        if has_carry:
            st0, lo = args[0], 0
        else:
            st0, lo = _partial(chunks[0]), 1

        def body(r, st):
            chunk = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, r, 0, keepdims=False), stacked)
            return grouped_aggregate(
                concat_batches([st, _partial(chunk)]), key_idx, aggs,
                mode="merge", output_capacity=cap_out, key_bounds=kb_t,
                allow_dense=True)

        return jax.lax.fori_loop(lo, len(chunks), body, st0), viol

    return fused_agg_wave


def _gathered(b: Batch, axis: str) -> Batch:
    from ..parallel.exchange import broadcast_batch
    return broadcast_batch(b, axis)


def _keep_first_shard(b: Batch, n: int) -> Batch:
    cap = b.capacity
    per = cap // n
    keep = jnp.arange(cap) < per
    return Batch(b.schema, b.columns, b.row_mask & keep)


def _host_col(typ, vocab):
    return Column(typ, jnp.zeros(1, dtype=jnp.int32),
                  jnp.zeros(1, dtype=bool), vocab)


def _apply_remap(codes: np.ndarray, remap: np.ndarray) -> np.ndarray:
    idx = np.where(codes >= 0, codes, len(remap) - 1)
    return remap[idx]


class DistributedRunner:
    """LocalRunner's multi-shard sibling: same SQL surface, data sharded
    over an n-device mesh (reference DistributedQueryRunner.java:76 boots N
    servers; here N shards of SPMD programs — SURVEY.md §2d)."""

    def __init__(self, catalogs=None, catalog: str = "tpch",
                 schema: str = "default", tpch_sf: float = 0.01,
                 n_devices: Optional[int] = None,
                 rows_per_batch: int = 1 << 16):
        from ..connectors.spi import CatalogManager
        from ..connectors.tpch import TpchConnector
        if catalogs is None:
            from ..connectors.tpcds import TpcdsConnector
            catalogs = CatalogManager()
            catalogs.register("tpch", TpchConnector(sf=tpch_sf))
            catalogs.register("tpcds", TpcdsConnector(sf=tpch_sf))
        self.session = Session(catalogs=catalogs, catalog=catalog,
                               schema=schema)
        self.mesh = make_mesh(n_devices)
        self.rows_per_batch = rows_per_batch
        self._seq = 0

    def execute(self, sql: str,
                properties: Optional[Dict[str, object]] = None,
                user: str = "", cancel_event=None) -> QueryResult:
        """Run one query on the mesh. The keyword surface matches
        ``ClusterRunner.execute``: ``properties`` overlays per-query
        session properties — validated through the declared registry,
        so an unknown or mistyped property fails the query instead of
        silently doing nothing on the SPMD path — ``user`` scopes the
        history record, and ``cancel_event`` interrupts between
        batches. SELECTs ride the compiled-plan cache
        (serving/plancache.py): a repeated statement skips
        parse/plan/optimize straight onto warm shard_map executables."""
        from ..serving.plancache import cached_plan, parse_cached
        from ..sql import ast as A
        stmt = parse_cached(sql)
        if not isinstance(stmt, A.Query):
            raise NotImplementedError(
                "DistributedRunner serves queries; use LocalRunner for "
                "session statements")
        session = self.session
        if properties:
            from ..config import validate_session_property
            overlay = {k: validate_session_property(k, v)
                       for k, v in properties.items()}
            session = dataclasses.replace(
                session,
                properties={**session.properties, **overlay})
        self._seq += 1
        qid = f"dq_{self._seq:06d}"
        import time as _time
        from ..obs.history import HISTORY
        t0 = _time.perf_counter()
        create_time = _time.time()
        error: Optional[str] = None
        rows = None
        flight = None
        fl_token = None
        if mesh_flight_on(session):
            flight = _flight.FlightRecorder(
                qid, int(self.mesh.devices.size))
            fl_token = _flight.CURRENT_FLIGHT.set(flight)
        try:
            with TRACER.span("query", query_id=qid, user=user,
                             mode="spmd", shards=self.mesh.devices.size):
                with TRACER.span("plan"):
                    plan = cached_plan(stmt, session, user=user)
                from .local import run_init_plans
                ex = DistributedExecutor(session,
                                         self.rows_per_batch, self.mesh)
                ex.cancel_event = cancel_event
                run_init_plans(ex, plan)
                root = plan.root
                batches = []
                for b in ex.run(root.child):
                    ex._check_cancel()
                    batches.append(b)
                ex.check_errors()
                with _sync_record("result-gather", kind="drain"):
                    rows = [r for b in batches for r in b.to_pylist()]
            return QueryResult(names=[f.name for f in root.fields],
                               types=[f.type for f in root.fields],
                               rows=rows)
        except Exception as e:
            error = str(e)
            raise
        finally:
            record = {
                "query_id": qid, "query": sql.strip(), "user": user,
                "state": "FAILED" if error is not None else "FINISHED",
                "error": error, "create_time": create_time,
                "elapsed_ms": round(
                    (_time.perf_counter() - t0) * 1e3, 3),
                "rows": None if rows is None else len(rows),
                "mode": "spmd",
            }
            if flight is not None:
                _flight.CURRENT_FLIGHT.reset(fl_token)
                attr = flight.finish(_time.perf_counter() - t0)
                record.update(_flight.history_fields(attr))
            # the SPMD path has no EventListenerManager; feed the
            # persistent query history directly so
            # system.runtime.completed_queries covers all three
            # executors (with the caller's user for audit attribution,
            # like the cluster path)
            HISTORY.add(record)

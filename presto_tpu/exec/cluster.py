"""Cluster runner: coordinator scheduling fragments onto worker nodes.

The coordinator half of the multi-host runtime (reference
presto-main/.../execution/scheduler/SqlQueryScheduler.java:112,281,533
stage tree + task launch; server/remotetask/HttpRemoteTask.java:100
task lifecycle over HTTP; execution/SqlStageExecution.java). The SPMD
mesh path (exec/distributed.py) is the ICI story — one process, XLA
collectives; this is the DCN story — independent worker processes, each
owning a device, exchanging pages over HTTP.

Scheduling model (reference NodeScheduler/UniformNodeSelector
simplified to uniform assignment):

- ``source`` fragments: splits round-robin over ACTIVE workers, one
  task per worker that received splits;
- ``fixed`` fragments: one task on every active worker, input pages
  hash-routed by the producer (buffer index = consumer partition);
- ``single`` fragments: one task on the least-loaded worker.

Failure handling (reference failuredetector/HeartbeatFailureDetector):
a background heartbeat pings ``/v1/info``; nodes failing
``max_consecutive`` pings are excluded from scheduling, and queries with
tasks on a dead node fail fast rather than hang.
"""
from __future__ import annotations

import json
import statistics
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..connectors.spi import Split
from ..obs.log import LOG
from ..obs.metrics import NODES, REGISTRY, TASKS
from ..obs.trace import TRACER
from ..planner import codec
from ..planner.fragmenter import (
    FragmentedPlan, OutputSpec, PlanFragment, fragment_plan,
)
from ..planner.plan import PlanNode, RemoteSourceNode, TableScanNode
from .local import QueryResult
from .runner import LocalRunner


class QueryFailedError(RuntimeError):
    pass


class HeartbeatFailureDetector:
    """Marks workers dead after consecutive failed pings (reference
    failuredetector/HeartbeatFailureDetector.java:77,360 — the
    exponential-decay rate collapsed to a consecutive-failure budget)."""

    def __init__(self, urls, interval_s: float = 5.0,
                 max_consecutive: int = 3, on_info=None):
        # ``urls`` may be a static list or a zero-arg callable returning
        # the current membership (discovery-fed, reference
        # DiscoveryNodeManager feeding the failure detector)
        self._source = urls if callable(urls) else (lambda: list(urls))
        self.interval_s = interval_s
        self.max_consecutive = max_consecutive
        self.failures: Dict[str, int] = {}
        #: optional ``(url, info_doc)`` callback on every successful
        #: ping — the heartbeat doubles as the node-state federator feed
        self.on_info = on_info
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    @property
    def urls(self) -> List[str]:
        return list(self._source())

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def ping(self, url: str) -> Optional[dict]:
        """The worker's ``/v1/info`` doc on success (always truthy),
        None on failure."""
        try:
            with urllib.request.urlopen(f"{url}/v1/info",
                                        timeout=5) as resp:
                return json.loads(resp.read()) or {"state": "ACTIVE"}
        except Exception:
            return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            for u in self.urls:
                info = self.ping(u)
                if info is not None:
                    self.failures[u] = 0
                    if self.on_info is not None:
                        self.on_info(u, info)
                else:
                    self.failures[u] = self.failures.get(u, 0) + 1

    def active(self) -> List[str]:
        return [u for u in self.urls
                if self.failures.get(u, 0) < self.max_consecutive]


class ClusterMemoryManager:
    """Coordinator-side memory guard (reference
    memory/ClusterMemoryManager.java + TotalReservationLowMemoryKiller):
    polls workers' heartbeat memory payloads; while the cluster-wide
    reservation exceeds ``limit_bytes``, kills the query holding the
    most memory (DELETE /v1/query/{id} on every worker)."""

    def __init__(self, runner: "ClusterRunner", limit_bytes: int,
                 interval_s: float = 0.5):
        self.runner = runner
        self.limit = limit_bytes
        self.interval_s = interval_s
        self.killed: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def poll_once(self) -> Dict[str, int]:
        totals: Dict[str, int] = {}
        for url in self.runner.detector.active():
            try:
                # single attempt, short timeout: the next 0.5s poll is
                # the retry, and enforcement must not stall on a worker
                # the failure detector hasn't evicted yet
                info = self.runner._request(f"{url}/v1/info",
                                            retries=0, timeout=5)
            except Exception:
                continue
            for qid, b in info.get("queryMemory", {}).items():
                totals[qid] = totals.get(qid, 0) + int(b)
        return totals

    def enforce(self, totals: Dict[str, int]) -> None:
        live = {q: b for q, b in totals.items() if q not in self.killed}
        if not live or sum(live.values()) <= self.limit:
            return
        victim = max(live, key=live.get)
        self.killed[victim] = live[victim]
        LOG.log("query_killed_low_memory", query_id=victim,
                reserved_bytes=live[victim], limit_bytes=self.limit)
        for url in list(self.runner.worker_urls):
            try:
                self.runner._request(f"{url}/v1/query/{victim}",
                                     method="DELETE", retries=0,
                                     timeout=5)
            except Exception:
                continue

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.enforce(self.poll_once())


_STRAGGLERS_DETECTED = REGISTRY.counter("straggler_detected_total")
_SKEWED_STAGES = REGISTRY.counter("skewed_stage_total")


class StageMonitor:
    """Coordinator-side progress + straggler/skew detection over task
    status docs (the role of the reference's SqlStageExecution task
    stats aggregation feeding the low-memory killer and the webapp's
    stage timelines; see tf.data's production straggler story for why
    this must be always-on, not a profiling mode).

    Fed by the status polls the collector already makes: per stage it
    tracks completion progress, flags a task as a straggler when its
    elapsed time exceeds ``straggler_ratio`` x the median of the
    stage's OTHER tasks (median-of-others keeps a 2-task stage
    flaggable), and flags a stage as skewed when its max per-partition
    output row count exceeds ``skew_ratio`` x the stage median (the
    mean is useless here: max/mean is bounded by the task count, so a
    3-task stage could never cross a 4x threshold). Findings
    land in the shared TaskRegistry (``system.runtime.tasks`` columns
    ``straggler``/``skew_ratio``), in counters
    (``straggler_detected_total``/``skewed_stage_total``) so tests can
    assert regressions, and in the structured log."""

    straggler_ratio = 3.0
    min_elapsed_ms = 25.0
    skew_ratio = 4.0
    min_stage_rows = 256

    def __init__(self, query_id: str):
        self.query_id = query_id
        self._stragglers: set = set()
        self._skew: Dict[int, float] = {}
        self.progress: Dict[int, float] = {}
        self.last_statuses: List[dict] = []

    @staticmethod
    def _stage_of(task_id: str) -> int:
        parts = task_id.split(".")
        return int(parts[1]) if len(parts) > 2 and parts[1].isdigit() \
            else 0

    def _by_stage(self, statuses: List[dict]) -> Dict[int, List[dict]]:
        out: Dict[int, List[dict]] = {}
        for st in statuses:
            tid = st.get("taskId")
            if tid:
                out.setdefault(self._stage_of(tid), []).append(st)
        return out

    def observe(self, statuses: List[dict]) -> None:
        self.last_statuses = statuses
        for fid, sts in self._by_stage(statuses).items():
            done = sum(1 for s in sts if s.get("state") == "FINISHED")
            self.progress[fid] = round(100.0 * done / len(sts), 1)
            for st in sts:
                # mirror worker status into the coordinator's registry:
                # system.runtime.tasks works against remote workers too
                TASKS.update(
                    st["taskId"], query_id=self.query_id, stage_id=fid,
                    state=st.get("state", ""),
                    elapsed_ms=float(st.get("elapsedMs") or 0.0),
                    output_rows=int(st.get("rowsOut") or 0),
                    output_bytes=int(st.get("bytesOut") or 0))
            elapsed = [float(s.get("elapsedMs") or 0.0) for s in sts]
            if len(elapsed) < 2:
                continue
            for i, st in enumerate(sts):
                tid = st["taskId"]
                if tid in self._stragglers:
                    continue
                others = elapsed[:i] + elapsed[i + 1:]
                med = statistics.median(others)
                if med >= self.min_elapsed_ms \
                        and elapsed[i] > self.straggler_ratio * med:
                    self._stragglers.add(tid)
                    _STRAGGLERS_DETECTED.inc()
                    TASKS.update(tid, straggler=True)
                    LOG.log("straggler_detected",
                            query_id=self.query_id, task_id=tid,
                            stage_id=fid,
                            elapsed_ms=round(elapsed[i], 1),
                            stage_median_ms=round(med, 1))

    def finalize(self, statuses: List[dict]) -> Dict[str, object]:
        """Final pass once every task reached a terminal state: one
        more straggler sweep over frozen elapsed values (a query that
        finished within one long-poll never hit ``observe``), then
        per-stage output-row skew. Returns the summary that rides the
        query-history record."""
        if statuses:
            self.observe(statuses)
        for fid, sts in self._by_stage(self.last_statuses).items():
            if fid in self._skew or len(sts) < 2:
                continue
            rows = [float(s.get("rowsOut") or 0.0) for s in sts]
            total = sum(rows)
            if total < self.min_stage_rows:
                continue
            # floor the median at one row: an all-in-one-partition
            # stage must flag with a FINITE ratio (inf would leak
            # non-strict "Infinity" tokens into the JSONL history sink
            # and the structured log)
            ratio = max(rows) / max(statistics.median(rows), 1.0)
            if ratio >= self.skew_ratio:
                self._skew[fid] = round(ratio, 2)
                _SKEWED_STAGES.inc()
                for st in sts:
                    TASKS.update(st["taskId"], skew_ratio=round(ratio, 2))
                LOG.log("stage_skew_detected", query_id=self.query_id,
                        stage_id=fid, skew_ratio=round(ratio, 2),
                        rows=[int(r) for r in rows])
        return self.summary()

    def summary(self) -> Dict[str, object]:
        return {"progress": dict(sorted(self.progress.items())),
                "stragglers": sorted(self._stragglers),
                "skewed_stages": dict(sorted(self._skew.items()))}


class ClusterRunner:
    """Executes SELECT queries across worker processes; everything else
    (DDL, SET, EXPLAIN) falls through to the embedded LocalRunner."""

    def __init__(self, worker_urls: Optional[List[str]] = None,
                 catalogs=None,
                 catalog: str = "tpch", schema: str = "default",
                 tpch_sf: float = 0.01, rows_per_batch: int = 1 << 17,
                 heartbeat: bool = True, discovery=None):
        # static URL list OR discovery-fed dynamic membership (reference
        # DiscoveryNodeManager: workers join by announcing, any time)
        self.discovery = discovery
        self._static_urls = list(worker_urls or ())
        self.local = LocalRunner(catalogs=catalogs, catalog=catalog,
                                 schema=schema, tpch_sf=tpch_sf,
                                 rows_per_batch=rows_per_batch)
        self.session = self.local.session
        self.rows_per_batch = rows_per_batch
        self._seq = 0
        #: worker url -> node id learned from /v1/info (node federator)
        self._node_ids: Dict[str, str] = {}
        NODES.update("coordinator", state="ACTIVE", coordinator=True,
                     uri="", active_tasks=0, mem_pool_peak_bytes=0)
        self.detector = HeartbeatFailureDetector(
            self._current_urls, on_info=self._note_node_info)
        self._heartbeat_on = bool(heartbeat)
        if heartbeat:
            self.detector.start()
        self.memory_manager: Optional[ClusterMemoryManager] = None
        limit = self.session.properties.get("cluster_memory_limit")
        if limit:
            self.enable_memory_manager(int(limit))

    def enable_memory_manager(self, limit_bytes: int,
                              interval_s: float = 0.5) -> None:
        self.memory_manager = ClusterMemoryManager(self, limit_bytes,
                                                   interval_s)
        self.memory_manager.start()

    def _current_urls(self) -> List[str]:
        if self.discovery is not None:
            return self.discovery.active_urls()
        return list(self._static_urls)

    @property
    def worker_urls(self) -> List[str]:
        return self._current_urls()

    # -- node-state federation (system.runtime.nodes) ------------------------
    def _note_node_info(self, url: str, info: dict) -> None:
        """Fold one worker's ``/v1/info`` doc into the process-wide
        node registry — the feed of ``system.runtime.nodes`` and of the
        node-labeled series on the coordinator's ``/v1/metrics``."""
        nid = str(info.get("nodeId") or url)
        self._node_ids[url] = nid
        tasks = info.get("tasks") or {}
        NODES.update(nid, state=str(info.get("state", "ACTIVE")),
                     coordinator=False, uri=url,
                     active_tasks=int(tasks.get("RUNNING", 0) or 0),
                     mem_pool_peak_bytes=int(
                         info.get("memPoolPeakBytes", 0) or 0))

    def poll_nodes(self, urls: Optional[List[str]] = None) -> None:
        """One synchronous federation sweep (the background heartbeat
        does the same continuously when enabled); unreachable workers
        keep their last heartbeat timestamp so their age grows."""
        for url in (urls if urls is not None else self.worker_urls):
            try:
                info = self._request(f"{url}/v1/info", retries=0,
                                     timeout=5)
            except Exception:
                nid = self._node_ids.get(url)
                if nid:
                    NODES.update(nid, seen=False, state="UNREACHABLE")
                continue
            self._note_node_info(url, info)

    # -- HTTP helpers --------------------------------------------------------
    #: transient-failure budget for one remote-task call (reference
    #: server/remotetask/RequestErrorTracker.java wraps every remote-task
    #: request in retry-with-backoff; one socket blip must not fail a
    #: query with healthy workers)
    REQUEST_RETRIES = 4
    REQUEST_BACKOFF_S = 0.1

    def _request(self, url: str, method: str = "GET",
                 body: Optional[dict] = None,
                 retries: Optional[int] = None,
                 timeout: float = 10) -> dict:
        """Remote-task HTTP with retry/backoff. Retrying is safe because
        every mutating endpoint is idempotent (task PUT is an upsert on
        the worker, DELETE/abort tolerate repeats). Latency-sensitive
        callers (the memory manager's poll/kill loop) pass retries=0 —
        their next poll IS the retry. These are small-JSON control-plane
        calls (create/status/delete): the 10s timeout bounds a
        black-holed worker at ~a minute across the whole retry budget,
        not 5 minutes (result pages stream through a separate client)."""
        data = json.dumps(body).encode() if body is not None else None
        budget = self.REQUEST_RETRIES if retries is None else retries
        last: Optional[Exception] = None
        for attempt in range(budget + 1):
            if attempt:
                time.sleep(self.REQUEST_BACKOFF_S * (2 ** (attempt - 1)))
            req = urllib.request.Request(url, data=data, method=method)
            if data is not None:
                req.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                if e.code >= 500 and attempt < budget:
                    last = e
                    continue
                raise
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError) as e:
                # transport-level failure: retry with backoff; the
                # heartbeat failure detector owns the
                # permanently-dead-worker verdict
                last = e
                if attempt >= budget:
                    break
                continue
        raise QueryFailedError(
            f"remote task request failed after "
            f"{budget + 1} attempts: {url}: {last}")

    # -- public API ----------------------------------------------------------
    def execute(self, sql: str) -> QueryResult:
        from ..sql.parser import parse_statement
        from ..sql import ast as A
        stmt = parse_statement(sql)
        if not isinstance(stmt, A.Query):
            return self.local.execute(sql)
        plan = self.local.plan(sql)
        # init plans (uncorrelated scalar subqueries) run on the
        # coordinator; their values ship inside every task update
        from .local import run_init_plans, _Executor
        ex = _Executor(self.session, self.rows_per_batch)
        run_init_plans(ex, plan)
        init_values = ex.init_values
        fragmented = fragment_plan(plan.root)
        return self._run_fragments(fragmented, init_values, sql)

    # -- scheduling ----------------------------------------------------------
    def _run_fragments(self, fp: FragmentedPlan,
                       init_values: List[object],
                       sql: str = "") -> QueryResult:
        workers = self.detector.active()
        if not workers:
            raise QueryFailedError("no active workers")
        self._seq += 1
        qid = f"cq_{self._seq:06d}"
        REGISTRY.counter("cluster_queries_total").inc()
        if not self._heartbeat_on:
            # no background heartbeat federating node state (embedded/
            # test setups): one synchronous sweep keeps
            # system.runtime.nodes fresh; with the heartbeat on, its
            # 5s on_info feed already does this without adding N RTTs
            # to every query
            self.poll_nodes(workers)
        from ..connectors.system import QueryLogEntry
        from ..events import QueryCompletedEvent
        entry = QueryLogEntry(qid, "RUNNING", sql.strip(), 0.0,
                              create_time=time.time())
        with self.local._state_lock:
            self.local.query_log.append(entry)
            # same bound LocalRunner.execute applies: a cluster-only
            # coordinator must not grow the log without limit
            if len(self.local.query_log) > 1000:
                del self.local.query_log[:-500]
        monitor = StageMonitor(qid)
        t0 = time.perf_counter()
        error: Optional[str] = None
        try:
            with TRACER.span("query", query_id=qid, mode="cluster",
                             workers=len(workers)):
                out = self._schedule_and_collect(
                    fp, init_values, workers, qid, monitor)
            entry.state = "FINISHED"
            return out
        except Exception as e:
            entry.state = "FAILED"
            error = str(e)
            raise
        finally:
            entry.elapsed_ms = (time.perf_counter() - t0) * 1e3
            entry.error = error
            summary = monitor.summary()
            history = {
                "query_id": qid, "query": entry.query, "user": "",
                "state": entry.state, "error": error,
                "error_code": None, "create_time": entry.create_time,
                "elapsed_ms": round(entry.elapsed_ms, 3),
                "mode": "cluster", "plan_summary": " | ".join(
                    f"stage{f.id}[{f.partitioning}]"
                    for f in fp.fragments),
                "stages": summary,
                "operators": [
                    {"operator": "task " + str(st.get("taskId", "")),
                     "rows": int(st.get("rowsOut") or 0),
                     "bytes": int(st.get("bytesOut") or 0),
                     "batches": 0,
                     "wall_ms": float(st.get("elapsedMs") or 0.0)}
                    for st in monitor.last_statuses],
            }
            self.local.events.query_completed(QueryCompletedEvent(
                query_id=qid, query=entry.query, user="",
                state=entry.state, elapsed_ms=entry.elapsed_ms,
                error=error, create_time=entry.create_time,
                history=history))
            if LOG.enabled:
                LOG.log("query_completed", query_id=qid, mode="cluster",
                        state=entry.state,
                        elapsed_ms=round(entry.elapsed_ms, 3),
                        error=error, **summary)

    def _schedule_and_collect(self, fp: FragmentedPlan,
                              init_values: List[object],
                              workers: List[str], qid: str,
                              monitor: Optional[StageMonitor] = None
                              ) -> QueryResult:
        # task counts per fragment
        consumer_of: Dict[int, int] = {}
        for f in fp.fragments:
            for node in _walk(f.root):
                if isinstance(node, RemoteSourceNode):
                    for fid in node.fragment_ids:
                        consumer_of[fid] = f.id
        task_count: Dict[int, int] = {}
        splits_for: Dict[int, List[List[Split]]] = {}
        for f in fp.fragments:
            if f.partitioning == "single":
                task_count[f.id] = 1
            elif f.partitioning == "fixed":
                task_count[f.id] = len(workers)
            else:   # source: split assignment decides
                assignment = self._assign_splits(f, workers)
                splits_for[f.id] = assignment
                task_count[f.id] = sum(1 for a in assignment if a)
        # create tasks upstream-first (fragments list is already in
        # dependency order: children were cut before their consumers)
        task_urls: Dict[int, List[str]] = {}
        all_tasks: List[str] = []
        try:
            for f in fp.fragments:
                n_buffers = task_count.get(consumer_of.get(f.id, -1), 1)
                sources = {
                    fid: task_urls[fid]
                    for node in _walk(f.root)
                    if isinstance(node, RemoteSourceNode)
                    for fid in node.fragment_ids
                }
                urls: List[str] = []
                with TRACER.span("stage", query_id=qid, stage_id=f.id,
                                 partitioning=f.partitioning):
                    # tasks created inside the stage span: their wire
                    # trace context parents them under this stage
                    if f.partitioning == "source":
                        assignment = splits_for[f.id]
                        part = 0
                        for w, splits in zip(workers, assignment):
                            if not splits:
                                continue
                            urls.append(self._create_task(
                                w, qid, f, part, n_buffers, splits,
                                sources, init_values))
                            part += 1
                    elif f.partitioning == "fixed":
                        for part, w in enumerate(workers):
                            urls.append(self._create_task(
                                w, qid, f, part, n_buffers, [], sources,
                                init_values))
                    else:
                        urls.append(self._create_task(
                            workers[0], qid, f, 0, n_buffers, [],
                            sources, init_values))
                task_urls[f.id] = urls
                all_tasks.extend(urls)
            return self._collect(fp, task_urls, all_tasks, monitor)
        finally:
            if monitor is not None:
                # final status sweep BEFORE the task DELETEs: frozen
                # elapsed/rows feed the last straggler pass, the skew
                # pass, and the query-history operator records
                monitor.finalize(self._task_statuses(all_tasks))
            self._harvest_spans(all_tasks)
            for u in all_tasks:
                try:
                    self._request(u, method="DELETE")
                except Exception:
                    pass

    def _task_statuses(self, all_tasks: List[str]) -> List[dict]:
        """Best-effort status fetch for every task (single attempt —
        this runs on the completion path, including after a failure, so
        a dead worker must cost ONE timeout, not one per task: the
        first unreachable task skips the rest of that worker)."""
        out: List[dict] = []
        dead: set = set()
        for u in all_tasks:
            base = u.split("/v1/task/")[0]
            if base in dead:
                continue
            try:
                out.append(self._request(u, retries=0, timeout=2))
            except Exception:
                dead.add(base)
        return out

    def _harvest_spans(self, all_tasks: List[str]) -> None:
        """Pull each task's spans (its share of this query's trace) back
        to the coordinator so distributed traces stitch; the tracer
        dedupes by span id, so in-process workers sharing the ring are
        harmless."""
        if not TRACER.enabled:
            return
        # one fetch per distinct WORKER: a task's span export is the
        # worker's whole share of the trace, so per-task fetches would
        # download K duplicate copies for import_spans to throw away
        by_worker: Dict[str, str] = {}
        for u in all_tasks:
            by_worker.setdefault(u.split("/v1/task/")[0], u)
        for u in by_worker.values():
            try:
                st = self._request(f"{u}?spans=1", retries=0, timeout=5)
            except Exception:
                continue
            TRACER.import_spans(st.get("spans") or [])

    def _assign_splits(self, f: PlanFragment,
                       workers: List[str]) -> List[List[Split]]:
        scan = next(n for n in _walk(f.root)
                    if isinstance(n, TableScanNode))
        conn = self.session.catalogs.get(scan.catalog)
        splits = conn.split_manager.splits(scan.table, len(workers))
        out: List[List[Split]] = [[] for _ in workers]
        for i, s in enumerate(splits):
            out[i % len(workers)].append(s)
        return out

    def _create_task(self, worker: str, qid: str, f: PlanFragment,
                     partition: int, n_buffers: int,
                     splits: List[Split], sources: Dict[int, List[str]],
                     init_values: List[object]) -> str:
        task_id = f"{qid}.{f.id}.{partition}"
        doc = {
            "fragment": codec.encode(f.root),
            "output": {
                "kind": f.output.kind if f.output else "single",
                "keys": list(f.output.keys) if f.output else [],
                "n_buffers": n_buffers,
            },
            "splits": [codec.encode(s) for s in splits],
            "sources": {str(k): v for k, v in sources.items()},
            "partition": partition,
            "session": {
                "catalog": self.session.catalog,
                "schema": self.session.schema,
                "properties": {
                    k: v for k, v in self.session.properties.items()
                    if isinstance(v, (str, int, float, bool))
                },
            },
            "init_values": codec.encode(list(init_values)),
            "rows_per_batch": self.rows_per_batch,
        }
        ctx = TRACER.context()
        if ctx is not None:
            # span context over the wire (the stage span is current):
            # the worker's task span joins this trace
            doc["trace"] = ctx
        self._request(f"{worker}/v1/task/{task_id}", method="PUT",
                      body=doc)
        return f"{worker}/v1/task/{task_id}"

    # -- result collection ---------------------------------------------------
    def _collect(self, fp: FragmentedPlan,
                 task_urls: Dict[int, List[str]],
                 all_tasks: List[str],
                 monitor: Optional[StageMonitor] = None) -> QueryResult:
        from .pages import deserialize_page
        root = fp.root
        (root_url,) = task_urls[root.id]
        out_node = root.root
        names = [f.name for f in out_node.fields]
        types = [f.type for f in out_node.fields]
        rows: List[tuple] = []
        token = 0
        while True:
            req = urllib.request.Request(
                f"{root_url}/results/0/{token}?max_wait=2")
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = resp.read()
                    complete = resp.headers.get(
                        "X-Buffer-Complete") == "true"
                    token = int(resp.headers.get("X-Next-Token", token))
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")
                self._fail_tasks(all_tasks)
                raise QueryFailedError(detail) from None
            except urllib.error.URLError as e:
                self._check_tasks(all_tasks)
                raise QueryFailedError(str(e)) from None
            from ..server.worker import unframe_pages
            for page in unframe_pages(body):
                rows.extend(deserialize_page(page).to_pylist())
            if complete:
                break
            self._check_tasks(all_tasks, monitor)
        return QueryResult(names=names, types=types, rows=rows)

    def _check_tasks(self, all_tasks: List[str],
                     monitor: Optional[StageMonitor] = None) -> None:
        # failure-path diagnostic probes: single attempt with a short
        # timeout — this path runs when something already looks wrong,
        # and burning the full retry budget per task against a dead
        # worker turns fail-fast into minutes of hanging. The liveness
        # polls double as the straggler monitor's status feed.
        statuses: List[dict] = []
        failed: Optional[dict] = None
        for u in all_tasks:
            try:
                st = self._request(u, retries=0, timeout=5)
            except Exception as e:
                raise QueryFailedError(
                    f"lost task {u}: {e}") from None
            statuses.append(st)
            if failed is None \
                    and st.get("state") in ("FAILED", "ABORTED"):
                failed = st
        if monitor is not None:
            monitor.observe(statuses)
        if failed is not None:
            raise QueryFailedError(
                f"task {failed.get('taskId')} failed: "
                f"{failed.get('error')}")

    def _fail_tasks(self, all_tasks: List[str]) -> None:
        try:
            self._check_tasks(all_tasks)
        except QueryFailedError as e:
            raise e
        except Exception:
            pass


def _walk(node: PlanNode):
    yield node
    for c in node.children:
        yield from _walk(c)
